"""TPU slice topologies.

The scheduler's "GPU count" analog is a *topology-shaped reservation*
(SURVEY.md §7.1): a pod slice like ``v4-32`` is 4 hosts × 4 chips wired into
one ICI domain and must be leased atomically.  This module is the registry
mapping topology names → (hosts, chips, ICI mesh shape) used by placement
groups, the mesh builder, and the collective layer.

Chip counts follow the public naming convention: the suffix is chip count
for v4/v5p (which have 2 TensorCores/chip, "megacore" on v4), and chips for
v5e/v6e as well (1 core/chip).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class SliceSpec:
    name: str                 # e.g. "v4-32"
    generation: str           # "v4"
    num_chips: int
    chips_per_host: int
    ici_mesh: Tuple[int, ...]  # physical ICI mesh shape (chips)
    megacore: bool            # 2 TensorCores fused per chip

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)


# chips per host and megacore by generation
_GEN = {
    "v2": (8, False), "v3": (8, False),
    "v4": (4, True), "v5p": (4, True),
    "v5e": (4, False), "v5litepod": (4, False),
    "v6e": (4, False),
}


def _default_mesh(num_chips: int) -> Tuple[int, ...]:
    """Factor a chip count into a near-cubic 3D torus shape (v4-style)."""
    if num_chips <= 4:
        return (num_chips,) if num_chips else (1,)
    best = (num_chips, 1, 1)
    for x in range(1, int(round(num_chips ** (1 / 3))) + 2):
        if num_chips % x:
            continue
        rest = num_chips // x
        for y in range(x, int(rest ** 0.5) + 1):
            if rest % y:
                continue
            cand = (x, y, rest // y)
            if max(cand) - min(cand) < max(best) - min(best):
                best = cand
    return tuple(sorted(best))


def slice_spec(topology: str) -> SliceSpec:
    """Parse ``v4-32`` / ``v5e-8`` / ``v5p-128`` style names."""
    m = re.fullmatch(r"(v\d+[a-z]*|v5litepod)-(\d+)", topology.strip().lower())
    if m is None:
        raise ValueError(f"unrecognized TPU topology {topology!r} "
                         "(expected e.g. 'v4-32', 'v5e-8')")
    gen, n = m.group(1), int(m.group(2))
    if gen not in _GEN:
        raise ValueError(f"unknown TPU generation {gen!r}")
    chips_per_host, megacore = _GEN[gen]
    return SliceSpec(name=topology, generation=gen, num_chips=n,
                     chips_per_host=min(chips_per_host, n),
                     ici_mesh=_default_mesh(n), megacore=megacore)


def detect_local_topology() -> Optional[SliceSpec]:
    """Best-effort: infer the attached slice from the jax device list."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    if GLOBAL_CONFIG.tpu_topology:
        return slice_spec(GLOBAL_CONFIG.tpu_topology)
    try:
        import jax
        devs = [d for d in jax.devices() if d.platform != "cpu"]
    except Exception:  # noqa: BLE001
        return None
    if not devs:
        return None
    kind = getattr(devs[0], "device_kind", "").lower()
    gen = "v4"
    for g in _GEN:
        if g in kind.replace(" ", ""):
            gen = g
    if "v5 lite" in kind or "v5e" in kind:
        gen = "v5e"
    return slice_spec(f"{gen}-{len(devs)}")


def ici_domain_label(slice_name: str, slice_idx: int = 0,
                     host_index: Optional[int] = None) -> Dict[str, str]:
    """Node labels marking co-membership in one ICI domain (for STRICT_PACK).

    ``host_index`` is the host's position along the slice's host dimension;
    the PG scheduler uses it to keep multi-host reservations on ICI-adjacent
    hosts (a contiguous window) instead of arbitrary members of the domain.
    """
    labels = {"ici_domain": f"{slice_name}/{slice_idx}",
              "slice_topology": slice_name}
    if host_index is not None:
        labels["slice_host"] = str(host_index)
    return labels
