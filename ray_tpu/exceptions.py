"""Public exception types.

Behavioral parity with the reference's ``python/ray/exceptions.py``
(SURVEY.md §3.2/§5.3): task errors propagate to ``get()`` wrapped in
``RayTaskError``; dead actors raise ``RayActorError``; a lost object whose
owner died raises ``OwnerDiedError`` (ownership is deliberately not re-homed —
SURVEY.md §5.3 notes this contract is load-bearing for refcount simplicity).
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get()`` on the caller.

    Carries the remote traceback text so the driver sees where the failure
    happened inside the worker (reference: ``RayTaskError.as_instanceof_cause``).
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Optional[BaseException] = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name, tb, cause=exc)

    def __reduce__(self):
        # cause travels when picklable; degraded to None otherwise
        try:
            import pickle
            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:  # noqa: BLE001
            cause = None
        return (RayTaskError, (self.function_name, self.traceback_str, cause))


class RayActorError(RayTpuError):
    """The actor died before or while executing the method."""

    def __init__(self, actor_id: str = "", reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id or '?'}: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorDiedError(RayActorError):
    pass


class ObjectLostError(RayTpuError):
    """Object can no longer be retrieved and could not be reconstructed."""

    def __init__(self, object_id: str = "", reason: str = "object lost"):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"object {object_id or '?'}: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class OwnerDiedError(ObjectLostError):
    """The process that owned this object died; borrowers cannot recover it."""


class ObjectStoreFullError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class WorkerCrashedError(RayTpuError):
    """A worker process died (e.g. SIGKILL) while running a task."""


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    """Placement group bundles cannot be satisfied by the cluster."""


class OutOfMemoryError(RayTpuError):
    """Task killed by the memory monitor under node memory pressure
    (reference: ray.exceptions.OutOfMemoryError)."""


class RaySystemError(RayTpuError):
    """Internal control-plane failure."""


class RayServeError(RayTpuError):
    """Serve-level failure (no replicas available, bad deployment, ...).

    Reference: ``ray.serve.exceptions.RayServeException``."""


# Reference-compatible alias.
RayServeException = RayServeError
