"""Multi-node test cluster fixture.

Reference: ``python/ray/cluster_utils.py`` (SURVEY.md §4) — the reference
starts multiple raylets as separate processes on one machine, each a logical
"node" with its own resources; tests exercise spillback scheduling, PG
spread, and node-failure recovery this way.  Here nodes are logical resource
views inside the single control plane, each with its own spawned worker
processes; ``remove_node`` kills that node's workers and marks its objects
lost, which drives the same recovery paths (lineage reconstruction, actor
restart, PG rescheduling).
"""

from __future__ import annotations

from typing import Dict, Optional

import ray_tpu
from ray_tpu._private import worker as _worker_mod


class NodeHandle:
    def __init__(self, node_id: str):
        self.node_id = node_id

    def __repr__(self) -> str:
        return f"NodeHandle({self.node_id[:8]})"


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self._nodes = []
        self.head_node: Optional[NodeHandle] = None
        if initialize_head:
            args = dict(head_node_args or {})
            ray_tpu.init(num_cpus=args.pop("num_cpus", 1),
                         resources=args.pop("resources", None), **args)
            w = _worker_mod.global_worker()
            self.head_node = NodeHandle(w.node_id)
            self._nodes.append(self.head_node)

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> NodeHandle:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        if num_tpus:
            res["TPU"] = float(num_tpus)
        w = _worker_mod.global_worker()
        resp = w.rpc("add_node", resources=res, labels=labels)
        node = NodeHandle(resp["node_id"])
        self._nodes.append(node)
        return node

    def drain_node(self, node: NodeHandle, deadline_s: float = 0.0,
                   reason: str = "preemption") -> None:
        """Provider-initiated preemption warning (DESIGN.md §4j): the
        node turns ``draining`` (no new placement; running work keeps
        going) and subscribers — the elasticity manager first among
        them — get the window to migrate before ``remove_node``."""
        w = _worker_mod.global_worker()
        w.rpc("node_draining", node_id=node.node_id,
              deadline_s=deadline_s, reason=reason)

    def remove_node(self, node: NodeHandle) -> None:
        w = _worker_mod.global_worker()
        w.rpc("remove_node", node_id=node.node_id)
        self._nodes = [n for n in self._nodes if n.node_id != node.node_id]

    def shutdown(self) -> None:
        ray_tpu.shutdown()
