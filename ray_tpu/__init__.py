"""ray_tpu: a TPU-native distributed computing framework.

The capabilities of the reference (Ray: tasks, actors, objects, placement
groups, collectives, Data/Train/Tune/RLlib/Serve) rebuilt TPU-first on
JAX/XLA/Pallas/pjit.  See SURVEY.md for the structural map and DESIGN.md for
where this implementation deliberately diverges from the reference.

Public core API parity (reference: ``python/ray/_private/worker.py``):
``init, shutdown, remote, get, put, wait, kill, cancel, get_actor,
is_initialized, nodes, cluster_resources, available_resources, timeline``.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple, Union

from ray_tpu import exceptions
from ray_tpu._private import rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.session import Session
from ray_tpu._private import protocol as _protocol
from ray_tpu._private import worker as _worker_mod
from ray_tpu.actor import ActorClass, ActorHandle, get_actor
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "remote", "get", "put", "wait", "kill", "cancel",
    "get_actor", "is_initialized", "nodes", "cluster_resources",
    "available_resources", "ObjectRef", "ActorHandle", "exceptions",
    "method", "timeline", "get_runtime_context", "__version__",
]

_init_lock = threading.Lock()
_head = None  # GcsServer when this process started the cluster


def _detect_tpu_chips() -> float:
    """Count local TPU chips without initializing JAX eagerly on workers."""
    override = os.environ.get("RTPU_NUM_TPUS")
    if override is not None:
        return float(override)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return 0.0
    try:
        import jax
        return float(len([d for d in jax.devices()
                          if d.platform not in ("cpu",)]))
    except Exception:  # noqa: BLE001 - no TPU runtime present
        return 0.0


def init(address: Optional[str] = None, *,
         num_cpus: Optional[int] = None, num_tpus: Optional[float] = None,
         resources: Optional[dict] = None, namespace: str = "default",
         log_to_driver: bool = True, _system_config: Optional[dict] = None,
         ignore_reinit_error: bool = False,
         _session_dir: Optional[str] = None, **_compat: Any):
    """Start (or connect to) a ray_tpu cluster. Reference: ``ray.init``.

    With no address, boots a head node in-process: control plane (GCS),
    object store, and an on-demand worker pool (SURVEY.md §3.1).
    """
    global _head
    with _init_lock:
        if _worker_mod.try_global_worker() is not None:
            if ignore_reinit_error:
                return _ctx()
            raise RuntimeError("ray_tpu.init() called twice "
                               "(pass ignore_reinit_error=True to allow)")
        GLOBAL_CONFIG.apply_system_config(_system_config)
        # persistent XLA compile cache for the driver process too;
        # effective even if jax is already imported (config knob),
        # harmless when no TPU is attached
        GLOBAL_CONFIG.apply_xla_cache_env(os.environ)
        if GLOBAL_CONFIG.xla_cache_dir and "jax" in sys.modules:
            try:
                sys.modules["jax"].config.update(
                    "jax_compilation_cache_dir",
                    GLOBAL_CONFIG.xla_cache_dir)
            except Exception:  # noqa: BLE001 - best effort
                pass
        from ray_tpu._private.gcs import GcsServer

        if address is None or address == "local":
            if _session_dir:
                # head restart over an existing session dir: GcsServer
                # restores the durable snapshot (GCS fault tolerance) and
                # surviving workers/actors reattach
                root, name = os.path.split(os.path.abspath(_session_dir))
                session = Session(root=root, name=name)
            else:
                session = Session()
            _protocol.set_authkey(session.auth_key())
            rtlog.setup("driver", session.log_dir)
            head_res = dict(resources or {})
            head_res["CPU"] = float(num_cpus if num_cpus is not None
                                    else (os.cpu_count() or 4))
            tpus = num_tpus if num_tpus is not None else _detect_tpu_chips()
            if tpus:
                head_res["TPU"] = float(tpus)
            _head = GcsServer(session, head_res)
            session.write_descriptor({"gcs": _head.rpc_path})
        elif address.startswith("ray://"):
            # remote-client mode through the TCP proxy (reference:
            # ray.init("ray://host:10001") — Ray Client)
            hostport = address[len("ray://"):]
            host, _, port = hostport.partition(":")
            key_hex = os.environ.get("RTPU_AUTH_KEY")
            if key_hex:
                _protocol.set_authkey(bytes.fromhex(key_hex))
            rtlog.setup("client", None)
            w = _worker_mod.Worker(None, role="driver",
                                   proxy_addr=(host, int(port or 10001)))
            w.namespace = namespace
            _worker_mod.set_global_worker(w)
            atexit.register(shutdown)
            return {"session_dir": None, "node_id": w.node_id,
                    "client": True}
        elif address == "auto":
            # attach to the latest session on this machine (reference:
            # ray.init(address="auto"))
            session = Session.latest()
            desc_pid = session.read_descriptor().get("head_pid") \
                or session.read_descriptor().get("pid")
            alive = False
            if desc_pid:
                try:
                    os.kill(desc_pid, 0)
                    alive = True
                except (ProcessLookupError, PermissionError):
                    pass
            if not alive:
                raise ConnectionError(
                    f"no running ray_tpu cluster (latest session "
                    f"{session.path} has no live head process)")
            _protocol.set_authkey(session.auth_key())
            rtlog.setup("driver", session.log_dir)
        else:
            # attach to an existing session (same machine)
            root, name = os.path.split(address)
            session = Session(root=root, name=name)
            _protocol.set_authkey(session.auth_key())
            rtlog.setup("driver", session.log_dir)

        w = _worker_mod.Worker(session, role="driver")
        w.namespace = namespace
        _worker_mod.set_global_worker(w)
        if _head is not None and log_to_driver and GLOBAL_CONFIG.log_to_driver:
            _head.log_sink = print
        atexit.register(shutdown)
        return _ctx()


def _ctx() -> dict:
    w = _worker_mod.global_worker()
    return {"session_dir": str(w.session.path), "node_id": w.node_id}


def shutdown() -> None:
    global _head
    with _init_lock:
        w = _worker_mod.try_global_worker()
        if w is None:
            return
        try:
            w.shutdown()
        finally:
            _worker_mod.set_global_worker(None)
        if _head is not None:
            _head.shutdown()
            _head = None
        try:
            atexit.unregister(shutdown)
        except Exception:  # noqa: BLE001
            pass


def is_initialized() -> bool:
    return _worker_mod.try_global_worker() is not None


# ----------------------------------------------------------------- decorator
def remote(*args: Any, **options: Any):
    """``@ray_tpu.remote`` for functions and classes (reference: ``ray.remote``)."""
    def wrap(obj):
        if isinstance(obj, type):
            return ActorClass(obj, _map_gpu(options))
        return RemoteFunction(obj, _map_gpu(options))

    if len(args) == 1 and callable(args[0]) and not options:
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return wrap


def _map_gpu(options: dict) -> dict:
    out = dict(options)
    if "num_gpus" in out:  # reference spelling → TPU chips
        out["num_tpus"] = out.pop("num_gpus")
    return out


def method(num_returns: int = 1):
    """Decorator to declare actor-method return arity (reference: ray.method)."""
    def deco(fn):
        fn.__ray_num_returns__ = num_returns
        return fn
    return deco


# ------------------------------------------------------------------ core ops
def put(value: Any) -> ObjectRef:
    return _worker_mod.global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    w = _worker_mod.global_worker()
    if hasattr(refs, "__ray_get__"):  # pg.ready() duck-typing
        return refs.__ray_get__(timeout)
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    return w.get(list(refs), timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None,
         fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait() expects a list of ObjectRefs")
    return _worker_mod.global_worker().wait(list(refs), num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _worker_mod.global_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    _worker_mod.global_worker().rpc(
        "cancel_task", task_id=_task_of(ref), force=force)


def _task_of(ref: ObjectRef) -> str:
    # return ids are minted per task; GCS keeps the mapping via lineage/running
    w = _worker_mod.global_worker()
    resp = w.rpc("find_task_of_object", object_id=str(ref.id))
    return resp["task_id"]


# --------------------------------------------------------------- state views
def nodes() -> List[dict]:
    return _worker_mod.global_worker().rpc("list_nodes")["nodes"]


def cluster_resources() -> dict:
    return _worker_mod.global_worker().rpc("cluster_resources")["total"]


def available_resources() -> dict:
    return _worker_mod.global_worker().rpc("cluster_resources")["available"]


def timeline(filename: Optional[str] = None,
             trace_id: Optional[str] = None) -> List[dict]:
    """Chrome-trace events (reference: ``ray timeline``, SURVEY.md §5.1).

    With ``trace_id``, returns only that request's causal tree — host
    spans across every process plus the device rows captured under it
    (``util/trace_assembly.py``; CLI: ``ray_tpu trace <trace_id>``)."""
    events = _worker_mod.global_worker().rpc("timeline")["events"]
    if trace_id is not None:
        from ray_tpu.util import trace_assembly
        events = trace_assembly.trace_events(events, trace_id)
    if filename:
        import json
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


class _RuntimeContext:
    @property
    def node_id(self) -> str:
        return _worker_mod.global_worker().node_id

    @property
    def worker_id(self) -> str:
        return _worker_mod.global_worker().worker_id

    @property
    def task_id(self) -> Optional[str]:
        return _worker_mod.global_worker().ctx.task_id

    def get_node_id(self) -> str:
        return self.node_id


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()
