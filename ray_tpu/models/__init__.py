"""Model zoo: flagship models for the benchmark baselines (BASELINE.md).

The reference framework ships no models (it benchmarks torch models inside
worker actors); here they are first-class so trainers, serving, and benches
share one implementation.

========== =========================== ============================
module     flagship                    baseline
========== =========================== ============================
gpt2       GPT-2 124M…1.5B             #5 tokens/s/chip (north star)
llama      Llama-2/3 recipe (RoPE/GQA)  modern decoder flagship
resnet     ResNet-50 (GN+WS, NHWC)     #2 images/s/chip
bert       BERT-base encoder           #4 Serve latency/QPS
moe_transformer  top-k routed MoE      expert-parallel flagship
vit        ViT-B/16                    vision classification
t5         t5.1.1-base enc-dec         seq2seq
========== =========================== ============================
"""

from ray_tpu.models import (bert, gpt2, llama, moe_transformer,  # noqa: F401
                            resnet, t5, vit)

REGISTRY = {
    "gpt2": gpt2,
    "llama": llama,
    "resnet": resnet,
    "bert": bert,
    "moe": moe_transformer,
    "vit": vit,
    "t5": t5,
}


def get_model(name: str):
    """Look up a model module by family name, "family/preset", or an
    unambiguous preset name (raises if several families define it)."""
    if name in REGISTRY:
        return REGISTRY[name]
    if "/" in name:
        family, _, preset = name.partition("/")
        mod = REGISTRY.get(family)
        if mod is None or preset not in getattr(mod, "PRESETS", {}):
            raise KeyError(f"unknown model {name!r}")
        return mod
    hits = [(fam, mod) for fam, mod in REGISTRY.items()
            if name in getattr(mod, "PRESETS", {})]
    if len(hits) == 1:
        return hits[0][1]
    if hits:
        raise KeyError(
            f"preset {name!r} is ambiguous across families "
            f"{sorted(f for f, _ in hits)}; use 'family/{name}'")
    raise KeyError(f"unknown model {name!r}; families: {sorted(REGISTRY)}")
