"""Llama-family decoder (RMSNorm + RoPE + GQA + SwiGLU), TPU-first.

No reference counterpart (Ray ships no models; SURVEY.md §2.5) — included
so the framework's flagship set covers the modern decoder recipe alongside
GPT-2.  Same architecture conventions as the public Llama-2/3 papers:
pre-RMSNorm, rotary position embeddings, grouped-query attention, SwiGLU
MLP, untied output head.  Layout follows gpt2.py: stacked per-layer params
+ ``lax.scan`` (pipeline-axis ready), bf16 activations / f32 params,
pluggable attention impls for long-context (ring/Ulysses/flash).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.models._common import normal_init, param_count  # noqa: F401

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_positions: int = 4096
    n_embd: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32          # < n_head → grouped-query attention
    ffn_dim: int = 11008         # SwiGLU hidden
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "dense"     # dense | flash | ring | ulysses
    context_axis: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def llama2_7b() -> LlamaConfig:
    return LlamaConfig()


def llama3_8b() -> LlamaConfig:
    return LlamaConfig(vocab_size=128256, n_embd=4096, n_layer=32,
                       n_head=32, n_kv_head=8, ffn_dim=14336,
                       rope_theta=500000.0, max_positions=8192)


def tiny(vocab: int = 128, seq: int = 64) -> LlamaConfig:
    return LlamaConfig(vocab_size=vocab, max_positions=seq, n_embd=64,
                       n_layer=2, n_head=4, n_kv_head=2, ffn_dim=128)


PRESETS = {"llama2-7b": llama2_7b, "llama3-8b": llama3_8b, "tiny": tiny}


# ------------------------------------------------------------------- params
def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    pd = cfg.param_dtype
    E, L = cfg.n_embd, cfg.n_layer
    kv_dim = cfg.n_kv_head * cfg.head_dim
    k = iter(jax.random.split(rng, 4 + 7 * L))
    scale = 0.02
    out_scale = 0.02 / math.sqrt(2 * L)

    def stack(shape, s=scale):
        return jnp.stack([normal_init(next(k), shape, pd, s)
                          for _ in range(L)])

    blocks = {
        "attn_norm": {"scale": jnp.ones((L, E), pd)},
        "wq": {"kernel": stack((E, E))},
        "wk": {"kernel": stack((E, kv_dim))},
        "wv": {"kernel": stack((E, kv_dim))},
        "wo": {"kernel": stack((E, E), out_scale)},
        "mlp_norm": {"scale": jnp.ones((L, E), pd)},
        "w_gate": {"kernel": stack((E, cfg.ffn_dim))},
        "w_up": {"kernel": stack((E, cfg.ffn_dim))},
        "w_down": {"kernel": stack((cfg.ffn_dim, E), out_scale)},
    }
    return {
        "wte": normal_init(next(k), (cfg.vocab_size, E), pd),
        "blocks": blocks,
        "norm_f": {"scale": jnp.ones((E,), pd)},
        "lm_head": {"kernel": normal_init(next(k), (E, cfg.vocab_size), pd)},
    }


# ------------------------------------------------------------------ forward
def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def _rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings over (B, T, H, D); rotates pairs (d, d+D/2)."""
    B, T, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]  # (1, T, 1, half)
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def _gqa_expand(kv: jax.Array, n_head: int) -> jax.Array:
    """(B, T, n_kv, D) → (B, T, n_head, D) by repeating KV groups."""
    B, T, n_kv, D = kv.shape
    if n_kv == n_head:
        return kv
    rep = n_head // n_kv
    return jnp.repeat(kv, rep, axis=2)


def _attention(q, k, v, cfg: LlamaConfig):
    if cfg.attn_impl == "dense":
        from ray_tpu.models.gpt2 import dense_causal_attention
        return dense_causal_attention(q, k, v, None)
    if cfg.attn_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, True)
    if cfg.attn_impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention_for_model
        return ring_attention_for_model(q, k, v, cfg,
                                        axis_name=cfg.context_axis)
    if cfg.attn_impl == "ulysses":
        from ray_tpu.ops.ulysses import ulysses_attention_for_model
        return ulysses_attention_for_model(q, k, v, cfg,
                                           axis_name=cfg.context_axis)
    raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")


def _block(x: jax.Array, lp: Params, cfg: LlamaConfig,
           collect_kv: bool = False):
    """One decoder block; with ``collect_kv`` also returns the post-RoPE
    pre-GQA-expand (k, v) — the SAME body serves training and the
    serving engine's prefill cache fill, so the paths cannot diverge."""
    B, T, E = x.shape
    H, D, KV = cfg.n_head, cfg.head_dim, cfg.n_kv_head
    h = _rms_norm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
    q = (h @ lp["wq"]["kernel"].astype(cfg.dtype)).reshape(B, T, H, D)
    k = (h @ lp["wk"]["kernel"].astype(cfg.dtype)).reshape(B, T, KV, D)
    v = (h @ lp["wv"]["kernel"].astype(cfg.dtype)).reshape(B, T, KV, D)
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    ke, ve = _gqa_expand(k, H), _gqa_expand(v, H)
    a = _attention(q, ke, ve, cfg).reshape(B, T, E)
    x = x + a @ lp["wo"]["kernel"].astype(cfg.dtype)
    h = _rms_norm(x, lp["mlp_norm"]["scale"], cfg.rms_eps)
    gate = jax.nn.silu(h @ lp["w_gate"]["kernel"].astype(cfg.dtype))
    up = h @ lp["w_up"]["kernel"].astype(cfg.dtype)
    out = x + (gate * up) @ lp["w_down"]["kernel"].astype(cfg.dtype)
    if collect_kv:
        return out, (k, v)
    return out


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """tokens (B, T) int32 → logits (B, T, vocab) f32."""
    x = params["wte"].astype(cfg.dtype)[tokens]
    block = partial(_block, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(carry, lp):
        return block(carry, lp), None

    x, _ = lax.scan(body, x, params["blocks"])
    x = _rms_norm(x, params["norm_f"]["scale"], cfg.rms_eps)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


# -------------------------------------------------- inference (KV cache)
def _rope_at(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding for single tokens at explicit positions.

    x (B, H, D); positions (B,) int32 — the absolute position of each
    sequence's token (decode caches post-RoPE keys, so each key is
    rotated once, at its own position)."""
    B, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[:, None, :]            # (B, 1, half)
    sin = jnp.sin(angles)[:, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def forward_prefill(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                    last_pos: Optional[jax.Array] = None):
    """Prefill forward: tokens (B, T) → (logits, k, v) with
    k/v (L, B, T, KV, D).  Keys are cached post-RoPE, values
    pre-GQA-expand (the paged decode attention expands groups itself) —
    the layout the serve/llm engine scatters into its pool.

    ``last_pos`` (traced scalar): logits only at that position as
    (B, V); None returns the full (B, T, V) — see gpt2.forward_prefill."""
    x = params["wte"].astype(cfg.dtype)[tokens]

    def body(carry, lp):
        return _block(carry, lp, cfg, collect_kv=True)

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    x = _rms_norm(x, params["norm_f"]["scale"], cfg.rms_eps)
    if last_pos is not None:
        x = lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    if last_pos is not None:
        logits = logits[:, 0]
    return logits.astype(jnp.float32), ks, vs


def forward_decode(params: Params, tokens: jax.Array, positions: jax.Array,
                   kv_pool: jax.Array, block_tables: jax.Array,
                   ctx_lens: jax.Array, cfg: LlamaConfig):
    """One decode step over the paged KV pool (see gpt2.forward_decode).

    kv_pool (N, L, 2, bs, KV, D); returns (logits (B, V) f32,
    new_k (L, B, KV, D), new_v (L, B, KV, D))."""
    from ray_tpu.ops.paged_attention import paged_attention_decode
    B = tokens.shape[0]
    E, H, D, KV = cfg.n_embd, cfg.n_head, cfg.head_dim, cfg.n_kv_head
    x = params["wte"].astype(cfg.dtype)[tokens]                 # (B, E)
    k_pools = kv_pool[:, :, 0].transpose(1, 0, 2, 3, 4)
    v_pools = kv_pool[:, :, 1].transpose(1, 0, 2, 3, 4)

    def body(carry, xs):
        x = carry
        lp, k_pool, v_pool = xs
        h = _rms_norm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
        q = (h @ lp["wq"]["kernel"].astype(cfg.dtype)).reshape(B, H, D)
        k = (h @ lp["wk"]["kernel"].astype(cfg.dtype)).reshape(B, KV, D)
        v = (h @ lp["wv"]["kernel"].astype(cfg.dtype)).reshape(B, KV, D)
        q = _rope_at(q, positions, cfg.rope_theta)
        k = _rope_at(k, positions, cfg.rope_theta)
        a = paged_attention_decode(q, k_pool, v_pool, block_tables,
                                   ctx_lens, k, v).reshape(B, E)
        x = x + a @ lp["wo"]["kernel"].astype(cfg.dtype)
        h = _rms_norm(x, lp["mlp_norm"]["scale"], cfg.rms_eps)
        gate = jax.nn.silu(h @ lp["w_gate"]["kernel"].astype(cfg.dtype))
        up = h @ lp["w_up"]["kernel"].astype(cfg.dtype)
        x = x + (gate * up) @ lp["w_down"]["kernel"].astype(cfg.dtype)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"], k_pools, v_pools))
    x = _rms_norm(x, params["norm_f"]["scale"], cfg.rms_eps)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    return logits.astype(jnp.float32), ks, vs


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: LlamaConfig) -> jax.Array:
    if "inputs" in batch:
        inp, tgt = batch["inputs"], batch["targets"]
    else:
        inp, tgt = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    logits = forward(params, inp, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0].mean()


# Sharding: attention/MLP matrices split fsdp×tensor; RoPE/norms replicated.
LLAMA_RULES = [
    (r".*wte$",                P("tensor", "fsdp")),
    (r".*blocks/w[qku].*kernel$",  P("pipeline", "fsdp", "tensor")),
    (r".*blocks/wv/kernel$",   P("pipeline", "fsdp", "tensor")),
    (r".*blocks/wo/kernel$",   P("pipeline", "tensor", "fsdp")),
    (r".*blocks/w_gate/kernel$", P("pipeline", "fsdp", "tensor")),
    (r".*blocks/w_up/kernel$", P("pipeline", "fsdp", "tensor")),
    (r".*blocks/w_down/kernel$", P("pipeline", "tensor", "fsdp")),
    (r".*norm.*scale$",        P(None)),
    (r".*lm_head/kernel$",     P("fsdp", "tensor")),
    (r".*", P(None)),
]
