"""GPT-2 family, TPU-first (flagship model for baseline #5, BASELINE.md).

The reference framework (Ray) ships no models — its GPT-2 benchmark runs
torch + DeepSpeed inside Train worker actors (reference:
``python/ray/train/``).  Here the model is a first-class citizen so the
trainer, the mesh layer, and the benchmarks have a common flagship.

Design notes (TPU-first, not a torch translation):
- Pure-JAX pytree params (nested dicts) — transparent to `ray_tpu.parallel.
  mesh` regex sharding rules, `jax.tree_util`, and Orbax checkpointing.
- Per-layer params are STACKED on a leading ``n_layer`` axis and the forward
  pass is a single ``lax.scan`` over blocks: one trace/compile of one block
  regardless of depth (compile-time O(1) in layers), and the leading axis is
  what pipeline parallelism shards.
- ``jax.checkpoint`` (remat) around each block trades FLOPs for HBM.
- bf16 activations / f32 params+optimizer by default: MXU-native.
- Attention is pluggable (``attn_impl``): dense causal (XLA fuses to a good
  kernel), or ring/Ulysses context-parallel kernels from ``ray_tpu.ops``.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]
AttnImpl = Callable[..., jax.Array]  # (q, k, v, config) -> out


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dtype: Any = jnp.bfloat16          # activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # full: recompute everything in bwd (min HBM).  dots: save matmul
    # outputs without batch dims (MLP/projections) and recompute only
    # attention.  attn: save ONLY the flash-attention residuals (out+lse,
    # tagged via checkpoint_name in ops/flash_attention.py) so the
    # rematerialized backward skips re-running the flash forward kernel —
    # measured v5e b32/s1024: the biggest recompute in the step; requires
    # attn_impl="flash".  Ignored when remat=False.
    remat_policy: str = "full"  # full | dots | attn
    # "auto" (default) resolves per backend: the Pallas flash kernel on
    # TPU — the overlap-scheduled train step's default, no longer a
    # bench-only config — and XLA dense elsewhere (interpret-mode Pallas
    # on CPU is a debugging tool, not a default).
    attn_impl: str = "auto"    # auto | dense | flash | blockwise | ring | ulysses
    # Decomposed collective matmuls (ops/collective_matmul.py): "auto"
    # routes the qkv/attn-out/MLP projections through chunked
    # ppermute-ring all-gather-matmul / matmul-reduce-scatter whenever
    # the ambient mesh has a model axis (seq or tensor > 1) and the
    # shapes divide; "off" keeps GSPMD's serialized collective legs.
    collective_matmul: str = "auto"  # auto | off
    # >0: compute the LM-head matmul + cross entropy in this many sequence
    # chunks under jax.checkpoint, so the (B, T, vocab) f32 logits never
    # materialize (peak activation drops by ~B*T*V*4/chunks bytes; the
    # chunk logits are recomputed in the backward).  0 = single fused CE.
    loss_chunks: int = 0
    # >0: chunk the LM head over the VOCAB axis instead (online-softmax
    # accumulation of per-chunk lse, jax.checkpoint per chunk): the
    # (B, T, V) logits AND the backward's dlogits never materialize —
    # each scan step touches (B, T, V/c).  Mutually exclusive with
    # loss_chunks.  0 = off.  (VERDICT r4 weak #3: the LM-head+CE block.)
    loss_vocab_chunks: int = 0
    context_axis: Optional[str] = None  # mesh axis for SP/CP ("context")
    pipeline_axis: Optional[str] = None  # mesh axis for PP ("pipeline")
    num_microbatches: int = 0  # 0 = auto (4x stages, divisor of batch)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# Presets (approx. parameter counts follow the GPT-2 paper sizes).
def gpt2_small() -> GPT2Config:   # 124M
    return GPT2Config(n_embd=768, n_layer=12, n_head=12)


def gpt2_medium() -> GPT2Config:  # 350M
    return GPT2Config(n_embd=1024, n_layer=24, n_head=16)


def gpt2_large() -> GPT2Config:   # 774M
    return GPT2Config(n_embd=1280, n_layer=36, n_head=20)


def gpt2_xl() -> GPT2Config:      # 1.5B — baseline #5 flagship
    return GPT2Config(n_embd=1600, n_layer=48, n_head=25)


def tiny(vocab: int = 256, seq: int = 64) -> GPT2Config:
    """Tiny config for tests and multi-chip dry-runs."""
    return GPT2Config(vocab_size=vocab, n_positions=seq, n_embd=64,
                      n_layer=2, n_head=4)


PRESETS = {"gpt2": gpt2_small, "gpt2-124m": gpt2_small,
           "gpt2-medium": gpt2_medium, "gpt2-large": gpt2_large,
           "gpt2-xl": gpt2_xl, "gpt2-1.5b": gpt2_xl, "tiny": tiny}


# ------------------------------------------------------------------- params
from ray_tpu.models._common import normal_init as _dense_init, param_count  # noqa: E402


def init_params(rng: jax.Array, cfg: GPT2Config) -> Params:
    """Initialize params; block leaves stacked on a leading n_layer axis."""
    pd = cfg.param_dtype
    E, H, L = cfg.n_embd, cfg.n_head, cfg.n_layer
    k = iter(jax.random.split(rng, 8 + 4 * L))

    def stack(f):
        return jnp.stack([f(next(k), i) for i in range(L)])

    blocks = {
        "ln_1": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "attn_qkv": {
            "kernel": stack(lambda kk, i: _dense_init(kk, (E, 3, E), pd)),
            "bias": jnp.zeros((L, 3, E), pd),
        },
        "attn_out": {
            # GPT-2 residual-scaled init: 1/sqrt(2*L)
            "kernel": stack(lambda kk, i: _dense_init(
                kk, (E, E), pd, 0.02 / math.sqrt(2 * L))),
            "bias": jnp.zeros((L, E), pd),
        },
        "ln_2": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "mlp_in": {
            "kernel": stack(lambda kk, i: _dense_init(kk, (E, 4 * E), pd)),
            "bias": jnp.zeros((L, 4 * E), pd),
        },
        "mlp_out": {
            "kernel": stack(lambda kk, i: _dense_init(
                kk, (4 * E, E), pd, 0.02 / math.sqrt(2 * L))),
            "bias": jnp.zeros((L, E), pd),
        },
    }
    return {
        "wte": _dense_init(next(k), (cfg.vocab_size, E), pd),
        "wpe": _dense_init(next(k), (cfg.n_positions, E), pd, 0.01),
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
    }


# ------------------------------------------------------------------ forward
def _layer_norm(x, scale, bias, eps=1e-5):
    # Pallas fused LN (ops/layer_norm.py) when the lane tiling allows it:
    # pins the residual stream to its natural E-minor layout and collapses
    # the LN fwd+bwd chain to one VMEM pass each (~4ms/step total at the
    # flagship bench shape; step-level impact there is ~neutral — XLA was
    # already fusing LN into neighbors — but the pinned layout keeps the
    # trace legible and protects shapes where XLA picks T-minor).
    if x.shape[-1] % 128 == 0:
        from ray_tpu.ops.layer_norm import layer_norm
        return layer_norm(x, scale, bias, eps)
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def dense_causal_attention(q, k, v, cfg: GPT2Config) -> jax.Array:
    """Reference attention: (B, T, H, D) → (B, T, H, D). XLA fuses this well
    on the MXU for moderate T; long-context paths use ray_tpu.ops kernels."""
    del cfg
    T = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def resolved_attn_impl(cfg: GPT2Config) -> str:
    """Concrete attention impl for ``attn_impl='auto'``: the Pallas flash
    kernel on TPU, XLA dense elsewhere."""
    if cfg.attn_impl == "auto":
        return "flash" if jax.default_backend() == "tpu" else "dense"
    return cfg.attn_impl


def _flash_tiles(seq_len: int) -> bool:
    """Whether the flash kernel's best block tiles ``seq_len`` — the
    same gate ``flash_attention_for_model`` uses for its dense
    fallback (an odd serving bucket must not crash the trace)."""
    from ray_tpu.ops.flash_attention import pick_block_size
    return seq_len % pick_block_size(seq_len) == 0


def _resolve_attn(cfg: GPT2Config) -> AttnImpl:
    cfg = dataclasses.replace(cfg, attn_impl=resolved_attn_impl(cfg))
    if cfg.attn_impl == "dense":
        return dense_causal_attention
    if cfg.attn_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention_for_model
        return flash_attention_for_model
    if cfg.attn_impl == "blockwise":
        from ray_tpu.ops.attention import blockwise_attention
        return lambda q, k, v, cfg: blockwise_attention(q, k, v, causal=True)
    if cfg.attn_impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention_for_model
        return partial(ring_attention_for_model, axis_name=cfg.context_axis)
    if cfg.attn_impl == "ulysses":
        from ray_tpu.ops.ulysses import ulysses_attention_for_model
        return partial(ulysses_attention_for_model, axis_name=cfg.context_axis)
    raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")


def _block(x: jax.Array, lp: Params, cfg: GPT2Config,
           attn: AttnImpl, collect_kv: bool = False):
    """One transformer block; with ``collect_kv`` also returns the
    per-head (k, v) — the SAME body serves training and the serving
    engine's prefill cache fill, so the two paths cannot diverge."""
    B, T, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    h = _layer_norm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"])
    qkv = jnp.einsum("bte,eck->btck",
                     h, lp["attn_qkv"]["kernel"].astype(cfg.dtype))
    qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype)
    # Named so remat_policy="attn" can pin it: re-projecting qkv is the one
    # matmul the rematerialized backward would otherwise re-run (the flash
    # kernel's q/k/v residuals flow from here).
    from jax.ad_checkpoint import checkpoint_name
    qkv = checkpoint_name(qkv, "attn_qkv")
    q, k, v = [qkv[:, :, i, :].reshape(B, T, H, D) for i in range(3)]
    # Pin the attention-region layout (DESIGN.md §4q / ACTIVATION_RULES):
    # heads shard over tensor, sequence-through-attention over context
    # (ring CP), per-head features replicated.  No-op without an
    # ambient mesh; GSPMD otherwise guesses from the qkv matmul.
    from ray_tpu.parallel import mesh as mesh_lib
    q = mesh_lib.constrain(q, "batch", "seq_attn", "heads", "kv")
    k = mesh_lib.constrain(k, "batch", "seq_attn", "heads", "kv")
    v = mesh_lib.constrain(v, "batch", "seq_attn", "heads", "kv")
    a = attn(q, k, v, cfg).reshape(B, T, E)
    a = a @ lp["attn_out"]["kernel"].astype(cfg.dtype) \
        + lp["attn_out"]["bias"].astype(cfg.dtype)
    x = x + a
    h = _layer_norm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"])
    h = h @ lp["mlp_in"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_in"]["bias"].astype(cfg.dtype)
    # MLP hidden shards over tensor (Megatron TP): pinned so the gelu
    # runs on the sharded layout instead of an all-gathered one.
    h = mesh_lib.constrain(h, "batch", "seq_attn", "mlp")
    h = jax.nn.gelu(h, approximate=True)
    h = h @ lp["mlp_out"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_out"]["bias"].astype(cfg.dtype)
    out = x + h
    if collect_kv:
        return out, (k, v)
    return out


# ---------------------------------------------------- overlap-scheduled path
def _manual_parallel_axes(cfg: GPT2Config, mesh, seq_len: int):
    """(sp, tp) when the decomposed/manual region should run, else None.

    The manual region is the overlap-scheduled block: residual stream
    sequence-sharded over (seq × tensor) between attention and MLP
    (Korthikanti et al. 2022 — norms/residual adds never replicate
    work), with the boundary all-gather / reduce-scatter legs folded
    into the projection matmuls as ppermute rings
    (ops/collective_matmul.py) so they hide behind compute.

    A mesh with ``seq > 1`` REQUIRES this path (the axis has no GSPMD
    fallback semantics) — incompatible shapes raise.  ``tensor``-only
    meshes fall back to GSPMD's serialized collectives when the shapes
    don't divide (heads not divisible by tp), preserving the old
    behavior for exotic head counts.
    """
    if cfg.collective_matmul == "off" or mesh is None:
        return None
    from ray_tpu.ops.collective_matmul import model_parallel_sizes
    shape = dict(mesh.shape)
    sp, tp = model_parallel_sizes(mesh)
    if sp * tp == 1:
        return None
    from ray_tpu._private.jax_compat import shard_map_available
    impl = resolved_attn_impl(cfg)
    ok = (shard_map_available()
          and shape.get("context", 1) == 1
          and shape.get("pipeline", 1) == 1
          and impl not in ("ring", "ulysses")
          and cfg.n_head % tp == 0
          and cfg.n_embd % tp == 0 and (4 * cfg.n_embd) % tp == 0
          and seq_len % (sp * tp) == 0)
    if not ok:
        if sp > 1:
            raise ValueError(
                f"mesh has seq={sp} but the sequence-parallel region "
                f"cannot run: needs shard_map, context=pipeline=1, a "
                f"non-ring/ulysses attn_impl (have {impl!r}), heads/"
                f"embed divisible by tensor={tp}, and seq_len "
                f"({seq_len}) divisible by seq*tensor ({sp * tp})")
        return None
    return sp, tp


def _block_manual(x: jax.Array, lp: Params, *, cfg: GPT2Config,
                  attn_name: str, sp: int, tp: int) -> jax.Array:
    """Per-shard transformer block (inside shard_map over the mesh).

    ``x``: (B_local, T_local, E) with T_local = T / (sp·tp) — the
    sequence-parallel residual stream.  Layer norms and residual adds
    run on local tokens only; the four projections are decomposed
    collective matmuls over the ``tensor`` ring (all-gather-matmul in,
    matmul-reduce-scatter out) so their collective legs overlap their
    own partial products; attention runs on the T/sp sequence chunk —
    the Pallas flash kernel (or dense) at full T when sp == 1, the
    ppermute KV ring over the ``seq`` axis when sp > 1 (ring attention
    IS flash attention's online-softmax update walked around the ring,
    so the seq axis composes with the flash block layout instead of
    fighting it)."""
    from ray_tpu.ops import collective_matmul as cm
    B, Tl, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    Hl = H // tp

    h = _layer_norm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"])
    wqkv = lp["attn_qkv"]["kernel"].astype(cfg.dtype).reshape(E, 3 * Hl * D)
    qkv = cm.all_gather_matmul(h, wqkv, "tensor", tp)     # (B, T/sp, 3E/tp)
    qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype).reshape(-1)
    Ts = Tl * tp                                          # = T / sp
    qkv = qkv.reshape(B, Ts, 3, Hl, D)
    from jax.ad_checkpoint import checkpoint_name
    qkv = checkpoint_name(qkv, "attn_qkv")
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if sp > 1:
        from ray_tpu.ops.ring_attention import ring_attention
        a = ring_attention(q, k, v, axis_name="seq", axis_size=sp,
                           causal=True)
    elif attn_name == "flash" and _flash_tiles(Ts):
        from ray_tpu.ops.flash_attention import flash_attention
        a = flash_attention(q, k, v, True)
    elif attn_name == "blockwise":
        from ray_tpu.ops.attention import blockwise_attention
        a = blockwise_attention(q, k, v, causal=True)
    else:
        from ray_tpu.ops.attention import dense_attention
        a = dense_attention(q, k, v, causal=True)
    wout = lp["attn_out"]["kernel"].astype(cfg.dtype).reshape(Hl * D, E)
    aout = cm.matmul_reduce_scatter(a.reshape(B, Ts, Hl * D), wout,
                                    "tensor", tp)         # (B, Tl, E)
    # biases ride AFTER the reduce-scatter: inside it they would be
    # summed tp times
    x = x + aout + lp["attn_out"]["bias"].astype(cfg.dtype)

    h = _layer_norm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"])
    m = cm.all_gather_matmul(
        h, lp["mlp_in"]["kernel"].astype(cfg.dtype), "tensor", tp)
    m = jax.nn.gelu(m + lp["mlp_in"]["bias"].astype(cfg.dtype),
                    approximate=True)
    mo = cm.matmul_reduce_scatter(
        m, lp["mlp_out"]["kernel"].astype(cfg.dtype), "tensor", tp)
    return x + mo + lp["mlp_out"]["bias"].astype(cfg.dtype)


def _manual_block_specs(cfg: GPT2Config):
    """shard_map in_specs for one layer's params in the manual region.

    Only ``tensor`` appears: fsdp-sharded dims are declared replicated,
    so GSPMD inserts the ZeRO-3 all-gather at the region boundary (and
    its transpose reduce-scatters the grads) — weight resharding stays
    GSPMD's job, activation collectives are ours."""
    from jax.sharding import PartitionSpec as P
    del cfg
    ln = {"scale": P(None), "bias": P(None)}
    return {
        "ln_1": dict(ln),
        "attn_qkv": {"kernel": P(None, None, "tensor"),
                     "bias": P(None, "tensor")},
        "attn_out": {"kernel": P("tensor", None), "bias": P(None)},
        "ln_2": dict(ln),
        "mlp_in": {"kernel": P(None, "tensor"), "bias": P("tensor")},
        "mlp_out": {"kernel": P("tensor", None), "bias": P(None)},
    }


def forward_hidden(params: Params, tokens: jax.Array,
                   cfg: GPT2Config) -> jax.Array:
    """tokens (B, T) int32 → final-LN hidden states (B, T, E) in cfg.dtype."""
    B, T = tokens.shape
    attn = _resolve_attn(cfg)
    x = params["wte"].astype(cfg.dtype)[tokens]
    # Arrays here are GLOBAL (GSPMD view) even when the sequence dim is
    # sharded over the context axis — only the attention impl drops into
    # shard_map (where chunk offsets come from lax.axis_index).
    x = x + params["wpe"].astype(cfg.dtype)[jnp.arange(T)]

    from ray_tpu.parallel import mesh as mesh_lib
    amb_mesh = mesh_lib.get_ambient_mesh()
    manual = _manual_parallel_axes(cfg, amb_mesh, T)
    if manual is not None:
        # Overlap-scheduled region: shard_map over the whole mesh, the
        # residual stream sequence-sharded over (seq × tensor), every
        # projection a decomposed collective matmul.  x enters/leaves
        # per-shard as (B_local, T/(sp·tp), E).
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ray_tpu._private.jax_compat import shard_map
        sp, tp = manual
        xspec = P(("data", "fsdp"), ("seq", "tensor"), None)
        block = shard_map(
            partial(_block_manual, cfg=cfg,
                    attn_name=resolved_attn_impl(cfg), sp=sp, tp=tp),
            mesh=amb_mesh, in_specs=(xspec, _manual_block_specs(cfg)),
            out_specs=xspec, check_vma=False)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(amb_mesh,
                             mesh_lib.activation_spec("batch", "seq",
                                                      "embed")))
    else:
        block = partial(_block, cfg=cfg, attn=attn)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        elif cfg.remat_policy in ("attn", "attn_qkv"):
            # the saved names are tagged only inside the flash vjp; with
            # any other impl — or a shape where the flash hook falls
            # back to dense, or the seq>1 KV ring — this policy would
            # silently behave as full remat
            sp = 1 if manual is None else manual[0]
            flash_runs = (resolved_attn_impl(cfg) == "flash"
                          and sp == 1 and _flash_tiles(T // sp))
            if not flash_runs:
                raise ValueError(
                    "remat_policy='attn' requires attn_impl='flash' "
                    "with a flash-tileable sequence length and no "
                    "seq-axis KV ring (the policy's saved names exist "
                    "only inside the flash kernel's vjp)")
            # "attn": save the flash out + compact lse residuals so the
            # backward never re-runs the attention kernel (cheap: ~52MB
            # per GPT-2-small layer at b32/s1024).  "attn_qkv" also pins
            # the qkv projection — the one matmul the replay would re-run
            # — at (B,T,3E) bf16 per layer; right for small models,
            # OOMs ≥ gpt2-medium at b32/s1024 on 16GB chips.  (Pinning
            # the kernel-layout q/k/v instead measured +15ms on the
            # forward scan — see step_breakdown_r04.md.)
            names = ["flash_attn_out", "flash_attn_lse"]
            if cfg.remat_policy == "attn_qkv":
                names.append("attn_qkv")
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_only_these_names(*names))
        elif cfg.remat_policy == "full":
            block = jax.checkpoint(block)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                f"(expected 'full' or 'dots')")

    def scan_body(carry, lp):
        return block(carry, lp), None

    pp_mesh = None
    if cfg.pipeline_axis is not None:
        from ray_tpu.parallel import mesh as mesh_lib
        pp_mesh = mesh_lib.get_ambient_mesh()
        if pp_mesh is None:
            # Loud, not silent: tracing with PP configured but no ambient
            # mesh would bake a non-pipelined program into the jit cache.
            raise RuntimeError(
                "cfg.pipeline_axis is set but no ambient mesh is installed; "
                "trace inside ray_tpu.parallel.mesh.ambient_mesh(mesh) "
                "(spmd.build_train_program does this)")
    if pp_mesh is not None and pp_mesh.shape[cfg.pipeline_axis] > 1:
        # Pipeline-parallel block stack: stages ride ppermute over the
        # pipeline mesh axis; within a stage, the usual scan over its layer
        # slice.  Remat stays per-block (scan_body), not per-stage.
        from ray_tpu.parallel import pipeline as pp_lib
        S = pp_mesh.shape[cfg.pipeline_axis]
        staged = pp_lib.stack_stages(params["blocks"], S)
        M = cfg.num_microbatches or pp_lib.pick_num_microbatches(B, S)

        def stage_fn(sp, xm):
            y, _ = lax.scan(scan_body, xm, sp)
            return y

        x = pp_lib.merge_microbatches(pp_lib.pipeline_apply(
            stage_fn, staged, pp_lib.split_microbatches(x, M),
            mesh=pp_mesh, axis=cfg.pipeline_axis, remat=False))
    else:
        x, _ = lax.scan(scan_body, x, params["blocks"])
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return x


def forward(params: Params, tokens: jax.Array,
            cfg: GPT2Config) -> jax.Array:
    """tokens (B, T) int32 → logits (B, T, vocab) in f32."""
    x = forward_hidden(params, tokens, cfg)
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(cfg.dtype))
    # Vocab dim shards over tensor (the wte is tensor-sharded on vocab):
    # pinned so the (B, T, V) f32 logits never replicate.
    from ray_tpu.parallel import mesh as mesh_lib
    logits = mesh_lib.constrain(logits, "batch", None, "vocab")
    return logits.astype(jnp.float32)


def _chunked_ce(x: jax.Array, wte: jax.Array, tgt: jax.Array,
                n_chunks: int) -> jax.Array:
    """Mean next-token NLL with the LM head applied per sequence chunk.

    Each chunk's (B, T/c, V) logits live only inside one checkpointed scan
    step (recomputed in the backward) — the full-sequence logits tensor
    never exists in HBM.
    """
    B, T, E = x.shape
    if T % n_chunks:
        raise ValueError(f"seq len {T} not divisible by loss_chunks "
                         f"{n_chunks}")
    tc_len = T // n_chunks
    xc = x.reshape(B, n_chunks, tc_len, E).swapaxes(0, 1)
    tc = tgt.reshape(B, n_chunks, tc_len).swapaxes(0, 1)

    @jax.checkpoint
    def body(acc, chunk):
        xcb, tcb = chunk
        logits = jnp.einsum("bte,ve->btv", xcb, wte).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        correct = jnp.take_along_axis(logits, tcb[..., None], -1)[..., 0]
        return acc + (lse - correct).sum(), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xc, tc))
    return total / (B * T)


def _vocab_chunked_ce(x: jax.Array, wte: jax.Array, tgt: jax.Array,
                      n_chunks: int) -> jax.Array:
    """Mean next-token NLL with the LM head applied per VOCAB chunk.

    Online-softmax over the vocab axis: each scan step computes the
    (B, T, V/c) logits for one slice of the vocabulary, folds them into a
    running logsumexp, and picks up the correct-class logit when the
    target falls in the slice.  Neither the (B, T, V) logits nor the
    backward's same-sized dlogits ever exist in HBM — the checkpointed
    chunk recomputes its slice.  V is padded up to a multiple of
    ``n_chunks`` with masked (-inf) columns.
    """
    B, T, E = x.shape
    V = wte.shape[0]
    vc_len = -(-V // n_chunks)            # ceil
    pad = vc_len * n_chunks - V
    if pad:
        wte = jnp.concatenate(
            [wte, jnp.zeros((pad, E), wte.dtype)], axis=0)
    wc = wte.reshape(n_chunks, vc_len, E)
    offsets = jnp.arange(n_chunks, dtype=jnp.int32) * vc_len

    @jax.checkpoint
    def body(carry, chunk):
        run_lse, correct = carry
        w, off = chunk
        logits = jnp.einsum("bte,ve->btv", x, w).astype(jnp.float32)
        # mask padded vocab columns out of the reduction
        valid = (off + jnp.arange(vc_len)) < V
        logits = jnp.where(valid[None, None, :], logits, -jnp.inf)
        chunk_lse = jax.nn.logsumexp(logits, axis=-1)
        run_lse = jnp.logaddexp(run_lse, chunk_lse)
        local = tgt - off                 # (B, T), may be out of range
        in_chunk = (local >= 0) & (local < vc_len)
        got = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vc_len - 1)[..., None], -1)[..., 0]
        correct = correct + jnp.where(in_chunk, got, 0.0)
        return (run_lse, correct), None

    init = (jnp.full((B, T), -jnp.inf, jnp.float32),
            jnp.zeros((B, T), jnp.float32))
    (lse, correct), _ = lax.scan(body, init, (wc, offsets))
    return (lse - correct).mean()


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: GPT2Config) -> jax.Array:
    """Next-token cross entropy. batch: {"tokens": (B, T+1) int32} or
    {"inputs","targets"} pair of (B, T)."""
    if "inputs" in batch:
        inp, tgt = batch["inputs"], batch["targets"]
    else:
        inp, tgt = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    if cfg.loss_chunks and cfg.loss_vocab_chunks:
        raise ValueError("loss_chunks and loss_vocab_chunks are exclusive")
    if cfg.loss_vocab_chunks:
        x = forward_hidden(params, inp, cfg)
        return _vocab_chunked_ce(x, params["wte"].astype(cfg.dtype), tgt,
                                 cfg.loss_vocab_chunks)
    if cfg.loss_chunks:
        x = forward_hidden(params, inp, cfg)
        return _chunked_ce(x, params["wte"].astype(cfg.dtype), tgt,
                           cfg.loss_chunks)
    # CE via logsumexp, NOT log_softmax: log_softmax materializes a second
    # (B,T,V) f32 tensor (6.6GB at the flagship bench shape) just to read
    # one element per row.  The correct-class logit is gathered from the
    # bf16 logits so the f32 convert has exactly one consumer (the lse
    # reduce) and XLA fuses it without materializing f32 logits at all
    # (trace-measured ~14ms/step, benchmarks/step_decompose.py).
    x = forward_hidden(params, inp, cfg)
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(cfg.dtype))
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    correct = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
    return (lse - correct.astype(jnp.float32)).mean()


# -------------------------------------------------- inference (KV cache)
def forward_prefill(params: Params, tokens: jax.Array, cfg: GPT2Config,
                    last_pos: Optional[jax.Array] = None
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill forward: tokens (B, T) → (logits, k, v) with
    k/v (L, B, T, H, D) — the per-layer KV the serving engine scatters
    into its paged pool (serve/llm, DESIGN.md §4g).

    ``last_pos`` (traced scalar): compute logits ONLY at that sequence
    position, returned as (B, V) — prompts are bucket-padded, so the
    full (B, T, V) head projection would be mostly wasted work and
    device→host traffic.  None returns the full (B, T, V)."""
    B, T = tokens.shape
    attn = _resolve_attn(cfg)
    x = params["wte"].astype(cfg.dtype)[tokens]
    x = x + params["wpe"].astype(cfg.dtype)[jnp.arange(T)]

    def body(carry, lp):
        return _block(carry, lp, cfg, attn, collect_kv=True)

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if last_pos is not None:
        x = lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1)
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(cfg.dtype))
    if last_pos is not None:
        logits = logits[:, 0]
    return logits.astype(jnp.float32), ks, vs


def forward_decode(params: Params, tokens: jax.Array, positions: jax.Array,
                   kv_pool: jax.Array, block_tables: jax.Array,
                   ctx_lens: jax.Array,
                   cfg: GPT2Config) -> Tuple[jax.Array, jax.Array,
                                             jax.Array]:
    """One decode step over the paged KV pool.

    tokens/positions (B,) int32; kv_pool (N, L, 2, bs, H, D) — the
    engine's shm-backed block pool (read-only here: the new token's K/V
    is returned, not written); block_tables (B, MAXB) int32;
    ctx_lens (B,) int32.  Returns (logits (B, V) f32,
    new_k (L, B, H, D), new_v (L, B, H, D)).
    """
    from ray_tpu.ops.paged_attention import paged_attention_decode
    B = tokens.shape[0]
    E, H, D = cfg.n_embd, cfg.n_head, cfg.head_dim
    x = params["wte"].astype(cfg.dtype)[tokens]
    x = x + params["wpe"].astype(cfg.dtype)[positions]          # (B, E)
    # (N, L, 2, bs, H, D) → per-layer pools (L, N, bs, H, D)
    k_pools = kv_pool[:, :, 0].transpose(1, 0, 2, 3, 4)
    v_pools = kv_pool[:, :, 1].transpose(1, 0, 2, 3, 4)

    def body(carry, xs):
        x = carry
        lp, k_pool, v_pool = xs
        h = _layer_norm(x[:, None, :], lp["ln_1"]["scale"],
                        lp["ln_1"]["bias"])[:, 0]
        qkv = jnp.einsum("be,eck->bck",
                         h, lp["attn_qkv"]["kernel"].astype(cfg.dtype))
        qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype)
        q, k, v = [qkv[:, i, :].reshape(B, H, D) for i in range(3)]
        a = paged_attention_decode(q, k_pool, v_pool, block_tables,
                                   ctx_lens, k, v).reshape(B, E)
        a = a @ lp["attn_out"]["kernel"].astype(cfg.dtype) \
            + lp["attn_out"]["bias"].astype(cfg.dtype)
        x = x + a
        h = _layer_norm(x[:, None, :], lp["ln_2"]["scale"],
                        lp["ln_2"]["bias"])[:, 0]
        h = h @ lp["mlp_in"]["kernel"].astype(cfg.dtype) \
            + lp["mlp_in"]["bias"].astype(cfg.dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = h @ lp["mlp_out"]["kernel"].astype(cfg.dtype) \
            + lp["mlp_out"]["bias"].astype(cfg.dtype)
        return x + h, (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"], k_pools, v_pools))
    x = _layer_norm(x[:, None, :], params["ln_f"]["scale"],
                    params["ln_f"]["bias"])[:, 0]
    logits = jnp.einsum("be,ve->bv", x, params["wte"].astype(cfg.dtype))
    return logits.astype(jnp.float32), ks, vs


def flops_per_token(cfg: GPT2Config, seq_len: int) -> float:
    """Approximate train-step FLOPs/token (fwd+bwd ≈ 6*N + attention term)."""
    n = param_count_analytic(cfg)
    attn = 12 * cfg.n_layer * cfg.n_embd * seq_len  # 2*2*3 * L * E * T
    return 6 * n + attn


def param_count_analytic(cfg: GPT2Config) -> int:
    E, L, V, Pn = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.n_positions
    per_layer = 12 * E * E + 13 * E
    return V * E + Pn * E + L * per_layer + 2 * E
