"""BERT encoder family (BERT-base flagship) — baseline #4 (Serve latency/QPS).

The reference serves BERT via HuggingFace-on-torch inside Serve replica
actors (reference: ``python/ray/serve/`` examples).  TPU-first rebuild:

- Same stacked-layers + ``lax.scan`` layout as GPT-2 (one block compile),
  bidirectional attention (no causal mask), learned position embeddings,
  segment embeddings, post-LN like the original BERT.
- bf16 activations; f32 layer norms and softmax.
- Heads: masked-LM (tied embeddings) and sequence classification (pooler),
  selectable per call — a Serve deployment holds ONE param pytree and jits
  per (head, batch-shape); padding-bucketed shapes keep recompiles bounded.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_positions: int = 512
    type_vocab_size: int = 2
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    intermediate: int = 3072
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def bert_base() -> BertConfig:
    return BertConfig()


def bert_large() -> BertConfig:
    return BertConfig(n_embd=1024, n_layer=24, n_head=16, intermediate=4096)


def tiny(vocab: int = 128, seq: int = 64) -> BertConfig:
    return BertConfig(vocab_size=vocab, max_positions=seq, n_embd=64,
                      n_layer=2, n_head=4, intermediate=128)


PRESETS = {"bert-base": bert_base, "bert-large": bert_large, "tiny": tiny}


# ------------------------------------------------------------------- params
from ray_tpu.models._common import normal_init as _dense_init, param_count  # noqa: E402


def init_params(rng: jax.Array, cfg: BertConfig) -> Params:
    pd = cfg.param_dtype
    E, L, FF = cfg.n_embd, cfg.n_layer, cfg.intermediate
    k = iter(jax.random.split(rng, 12 + 4 * L))

    def stack(f):
        return jnp.stack([f(next(k)) for _ in range(L)])

    blocks = {
        "attn_qkv": {"kernel": stack(lambda kk: _dense_init(kk, (E, 3, E), pd)),
                     "bias": jnp.zeros((L, 3, E), pd)},
        "attn_out": {"kernel": stack(lambda kk: _dense_init(kk, (E, E), pd)),
                     "bias": jnp.zeros((L, E), pd)},
        "ln_1": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "mlp_in": {"kernel": stack(lambda kk: _dense_init(kk, (E, FF), pd)),
                   "bias": jnp.zeros((L, FF), pd)},
        "mlp_out": {"kernel": stack(lambda kk: _dense_init(kk, (FF, E), pd)),
                    "bias": jnp.zeros((L, E), pd)},
        "ln_2": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
    }
    return {
        "wte": _dense_init(next(k), (cfg.vocab_size, E), pd),
        "wpe": _dense_init(next(k), (cfg.max_positions, E), pd),
        "wtype": _dense_init(next(k), (cfg.type_vocab_size, E), pd),
        "ln_emb": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
        "blocks": blocks,
        "pooler": {"kernel": _dense_init(next(k), (E, E), pd),
                   "bias": jnp.zeros((E,), pd)},
        "cls": {"kernel": jnp.zeros((E, cfg.num_labels), pd),
                "bias": jnp.zeros((cfg.num_labels,), pd)},
        "mlm_ln": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
        "mlm_dense": {"kernel": _dense_init(next(k), (E, E), pd),
                      "bias": jnp.zeros((E,), pd)},
        "mlm_bias": jnp.zeros((cfg.vocab_size,), pd),
    }


# ------------------------------------------------------------------ forward
def _layer_norm(x, scale, bias, eps=1e-12):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return (((x32 - mu) * lax.rsqrt(var + eps)) * scale + bias).astype(x.dtype)


def _attention(q, k, v, mask, cfg: BertConfig):
    # (B, T, H, D) bidirectional; mask (B, T) 1=real token
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    bias = jnp.where(mask[:, None, None, :] > 0, 0.0,
                     jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32) + bias, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _block(x, mask, lp, cfg: BertConfig):
    B, T, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    qkv = jnp.einsum("bte,eck->btck", x,
                     lp["attn_qkv"]["kernel"].astype(cfg.dtype))
    qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype)
    q, k, v = [qkv[:, :, i, :].reshape(B, T, H, D) for i in range(3)]
    a = _attention(q, k, v, mask, cfg).reshape(B, T, E)
    a = a @ lp["attn_out"]["kernel"].astype(cfg.dtype) \
        + lp["attn_out"]["bias"].astype(cfg.dtype)
    x = _layer_norm(x + a, lp["ln_1"]["scale"], lp["ln_1"]["bias"])  # post-LN
    h = x @ lp["mlp_in"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_in"]["bias"].astype(cfg.dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = h @ lp["mlp_out"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_out"]["bias"].astype(cfg.dtype)
    return _layer_norm(x + h, lp["ln_2"]["scale"], lp["ln_2"]["bias"])


def encode(params: Params, tokens: jax.Array, cfg: BertConfig,
           attention_mask: Optional[jax.Array] = None,
           token_type_ids: Optional[jax.Array] = None) -> jax.Array:
    """tokens (B, T) int32 → hidden states (B, T, E)."""
    B, T = tokens.shape
    mask = attention_mask if attention_mask is not None \
        else jnp.ones((B, T), jnp.int32)
    types = token_type_ids if token_type_ids is not None \
        else jnp.zeros((B, T), jnp.int32)
    x = params["wte"].astype(cfg.dtype)[tokens]
    x = x + params["wpe"].astype(cfg.dtype)[jnp.arange(T)]
    x = x + params["wtype"].astype(cfg.dtype)[types]
    x = _layer_norm(x, params["ln_emb"]["scale"], params["ln_emb"]["bias"])

    block = partial(_block, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(carry, lp):
        return block(carry, mask, lp), None

    x, _ = lax.scan(body, x, params["blocks"])
    return x


def pooled(params: Params, tokens: jax.Array, cfg: BertConfig,
           attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """[CLS] pooled representation (B, E), tanh-activated."""
    h = encode(params, tokens, cfg, attention_mask)
    cls = h[:, 0, :]
    return jnp.tanh(cls @ params["pooler"]["kernel"].astype(cfg.dtype)
                    + params["pooler"]["bias"].astype(cfg.dtype))


def classify(params: Params, tokens: jax.Array, cfg: BertConfig,
             attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """Sequence classification logits (B, num_labels) f32 — the Serve path."""
    p = pooled(params, tokens, cfg, attention_mask)
    return (p.astype(jnp.float32)
            @ params["cls"]["kernel"].astype(jnp.float32)
            + params["cls"]["bias"].astype(jnp.float32))


def mlm_logits(params: Params, tokens: jax.Array, cfg: BertConfig,
               attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """Masked-LM logits (B, T, vocab) with tied embeddings."""
    h = encode(params, tokens, cfg, attention_mask)
    h = h @ params["mlm_dense"]["kernel"].astype(cfg.dtype) \
        + params["mlm_dense"]["bias"].astype(cfg.dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = _layer_norm(h, params["mlm_ln"]["scale"], params["mlm_ln"]["bias"])
    logits = jnp.einsum("bte,ve->btv", h, params["wte"].astype(cfg.dtype))
    return logits.astype(jnp.float32) + params["mlm_bias"].astype(jnp.float32)


def mlm_loss(params: Params, batch: Dict[str, jax.Array],
             cfg: BertConfig) -> jax.Array:
    """batch: tokens (B,T), targets (B,T), loss_mask (B,T) 1=masked position."""
    logits = mlm_logits(params, batch["tokens"], cfg,
                        batch.get("attention_mask"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["targets"][..., None],
                               axis=-1)[..., 0]
    m = batch["loss_mask"].astype(jnp.float32)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)


def classification_loss(params: Params, batch: Dict[str, jax.Array],
                        cfg: BertConfig) -> jax.Array:
    logits = classify(params, batch["tokens"], cfg,
                      batch.get("attention_mask"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, batch["labels"][:, None],
                                axis=-1).mean()
