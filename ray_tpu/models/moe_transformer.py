"""MoE decoder transformer — the expert-parallelism flagship.

No reference counterpart: Ray reaches MoE only through DeepSpeed inside
Train workers (SURVEY.md §2.4 "Expert parallelism — absent in core").  This
model pairs the GPT-2 attention stack with ``ray_tpu.ops.moe`` expert FFNs:
every layer's FFN is a top-k-routed expert bank whose weights carry a
leading ``num_experts`` axis sharded over the ``expert`` mesh axis — GSPMD
lowers token dispatch to all-to-alls over ICI.

Layer layout mirrors gpt2.py (stacked params + ``lax.scan``) so pipeline
parallelism (``pipeline_axis``) composes the same way; the aux losses ride
the scan as accumulated carries.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import gpt2 as gpt2_lib
from ray_tpu.models._common import normal_init, param_count  # noqa: F401
from ray_tpu.ops import moe as moe_lib

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    num_experts: int = 8
    expert_ff: int = 3072
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.001
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def moe_small() -> MoEConfig:  # ~8x124M-FFN experts
    return MoEConfig()


def tiny(vocab: int = 128, seq: int = 64, experts: int = 4) -> MoEConfig:
    return MoEConfig(vocab_size=vocab, n_positions=seq, n_embd=64, n_layer=2,
                     n_head=4, num_experts=experts, expert_ff=128)


PRESETS = {"moe-small": moe_small, "tiny": tiny}


# ------------------------------------------------------------------- params
def init_params(rng: jax.Array, cfg: MoEConfig) -> Params:
    pd = cfg.param_dtype
    E, H, L = cfg.n_embd, cfg.n_head, cfg.n_layer
    k = iter(jax.random.split(rng, 8 + 6 * L))

    def stack(f):
        return jnp.stack([f(next(k)) for _ in range(L)])

    def dense(kk, shape, scale=0.02):
        return normal_init(kk, shape, pd, scale)

    blocks = {
        "ln_1": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "attn_qkv": {"kernel": stack(lambda kk: dense(kk, (E, 3, E))),
                     "bias": jnp.zeros((L, 3, E), pd)},
        "attn_out": {"kernel": stack(lambda kk: dense(
            kk, (E, E), 0.02 / math.sqrt(2 * L))),
            "bias": jnp.zeros((L, E), pd)},
        "ln_2": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "moe": {
            "router": stack(lambda kk: dense(kk, (E, cfg.num_experts))),
            "w_in": stack(lambda kk: dense(
                kk, (cfg.num_experts, E, cfg.expert_ff),
                1.0 / math.sqrt(E))),
            "w_out": stack(lambda kk: dense(
                kk, (cfg.num_experts, cfg.expert_ff, E),
                1.0 / math.sqrt(cfg.expert_ff))),
        },
    }
    return {
        "wte": dense(next(k), (cfg.vocab_size, E)),
        "wpe": dense(next(k), (cfg.n_positions, E), 0.01),
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
    }


# ------------------------------------------------------------------ forward
def _block(x, lp, cfg: MoEConfig):
    """Attention (dense causal) + MoE FFN. Returns (y, (aux, z, dropped))."""
    B, T, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    h = gpt2_lib._layer_norm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"])
    qkv = jnp.einsum("bte,eck->btck", h,
                     lp["attn_qkv"]["kernel"].astype(cfg.dtype))
    qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype)
    q, kk, v = [qkv[:, :, i, :].reshape(B, T, H, D) for i in range(3)]
    a = gpt2_lib.dense_causal_attention(q, kk, v, None).reshape(B, T, E)
    a = a @ lp["attn_out"]["kernel"].astype(cfg.dtype) \
        + lp["attn_out"]["bias"].astype(cfg.dtype)
    x = x + a
    h = gpt2_lib._layer_norm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"])
    y, metrics = moe_lib.moe_ffn(
        h, lp["moe"]["router"].astype(jnp.float32),
        lp["moe"]["w_in"].astype(cfg.dtype),
        lp["moe"]["w_out"].astype(cfg.dtype),
        k=cfg.top_k, capacity_factor=cfg.capacity_factor)
    return x + y, (metrics.aux_loss, metrics.router_z_loss,
                   metrics.fraction_dropped)


def forward(params: Params, tokens: jax.Array, cfg: MoEConfig
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tokens (B, T) → (logits (B, T, vocab) f32, moe metrics)."""
    B, T = tokens.shape
    x = params["wte"].astype(cfg.dtype)[tokens]
    x = x + params["wpe"].astype(cfg.dtype)[jnp.arange(T)]

    block = partial(_block, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(carry, lp):
        y, m = block(carry, lp)
        return y, m

    x, (aux, z, dropped) = lax.scan(body, x, params["blocks"])
    x = gpt2_lib._layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(cfg.dtype))
    metrics = {"moe_aux_loss": aux.mean(), "moe_z_loss": z.mean(),
               "moe_fraction_dropped": dropped.mean()}
    return logits.astype(jnp.float32), metrics


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: MoEConfig) -> jax.Array:
    if "inputs" in batch:
        inp, tgt = batch["inputs"], batch["targets"]
    else:
        inp, tgt = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    logits, metrics = forward(params, inp, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return (nll.mean()
            + cfg.aux_loss_weight * metrics["moe_aux_loss"]
            + cfg.z_loss_weight * metrics["moe_z_loss"])


# Sharding rules: MoE rules first (most specific), then the transformer set.
from ray_tpu.parallel.mesh import TRANSFORMER_RULES as _TR  # noqa: E402

MOE_TRANSFORMER_RULES = moe_lib.MOE_RULES + _TR
