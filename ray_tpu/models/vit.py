"""Vision Transformer (ViT-B/16 flagship) — image classification on TPU.

Reference contrast: the reference ships no models; its vision benchmarks
run torchvision inside Train workers (reference: ``python/ray/train/``
examples).  TPU-first design notes:

- Patch embedding is a reshape + ONE matmul (``bhwc→b(hw)(ppc)`` then
  ``(ppc,E)``), not a conv — identical math for non-overlapping patches
  and it lands directly on the MXU with no im2col.
- Stacked per-layer params + ``lax.scan`` over blocks (one block compile),
  pre-LN transformer, learned position embeddings, CLS token readout —
  the ViT paper recipe.
- bf16 activations / f32 params, f32 layer norms and softmax; remat knob.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models._common import normal_init as _init

Params = Dict[str, Any]


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_b16() -> ViTConfig:    # 86M
    return ViTConfig()


def vit_l16() -> ViTConfig:    # 307M
    return ViTConfig(n_embd=1024, n_layer=24, n_head=16)


def tiny(image_size: int = 32, patch_size: int = 8,
         num_classes: int = 10) -> ViTConfig:
    return ViTConfig(image_size=image_size, patch_size=patch_size,
                     num_classes=num_classes, n_embd=64, n_layer=2, n_head=4)


PRESETS = {"vit-b16": vit_b16, "vit-l16": vit_l16, "tiny": tiny}


def init_params(rng: jax.Array, cfg: ViTConfig) -> Params:
    pd = cfg.param_dtype
    E, L, H = cfg.n_embd, cfg.n_layer, cfg.n_head
    P, C = cfg.patch_size, 3
    M = cfg.mlp_ratio * E
    k = iter(jax.random.split(rng, 10 + 4 * L))

    def stack(shape, scale=0.02):
        return jnp.stack([_init(next(k), shape, pd, scale) for _ in range(L)])

    blocks = {
        "ln_1": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "attn_qkv": {"kernel": stack((E, 3, E)),
                     "bias": jnp.zeros((L, 3, E), pd)},
        "attn_out": {"kernel": stack((E, E), 0.02 / math.sqrt(2 * L)),
                     "bias": jnp.zeros((L, E), pd)},
        "ln_2": {"scale": jnp.ones((L, E), pd), "bias": jnp.zeros((L, E), pd)},
        "mlp_in": {"kernel": stack((E, M)), "bias": jnp.zeros((L, M), pd)},
        "mlp_out": {"kernel": stack((M, E), 0.02 / math.sqrt(2 * L)),
                    "bias": jnp.zeros((L, E), pd)},
    }
    return {
        "patch_embed": {"kernel": _init(next(k), (P * P * C, E), pd),
                        "bias": jnp.zeros((E,), pd)},
        "cls_token": jnp.zeros((1, 1, E), pd),
        "pos_embed": _init(next(k), (cfg.num_patches + 1, E), pd, 0.02),
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
        "head": {"kernel": jnp.zeros((E, cfg.num_classes), pd),
                 "bias": jnp.zeros((cfg.num_classes,), pd)},
    }


def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _attention(q, k, v):
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block(x, lp, cfg: ViTConfig):
    B, T, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    h = _layer_norm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"])
    qkv = jnp.einsum("bte,eck->btck",
                     h, lp["attn_qkv"]["kernel"].astype(cfg.dtype))
    qkv = qkv + lp["attn_qkv"]["bias"].astype(cfg.dtype)
    q, k, v = [qkv[:, :, i, :].reshape(B, T, H, D) for i in range(3)]
    a = _attention(q, k, v).reshape(B, T, E)
    x = x + (a @ lp["attn_out"]["kernel"].astype(cfg.dtype)
             + lp["attn_out"]["bias"].astype(cfg.dtype))
    h = _layer_norm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"])
    h = h @ lp["mlp_in"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_in"]["bias"].astype(cfg.dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = h @ lp["mlp_out"]["kernel"].astype(cfg.dtype) \
        + lp["mlp_out"]["bias"].astype(cfg.dtype)
    return x + h


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """(B, H, W, C) → (B, num_patches, patch*patch*C): pure reshape —
    non-overlapping conv == matmul over flattened patches."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, gh * gw, patch * patch * C)


def forward(params: Params, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """images (B, H, W, C) float → logits (B, num_classes) f32."""
    B = images.shape[0]
    x = patchify(images.astype(cfg.dtype), cfg.patch_size)
    x = x @ params["patch_embed"]["kernel"].astype(cfg.dtype) \
        + params["patch_embed"]["bias"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype),
                           (B, 1, cfg.n_embd))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)[None]

    block = partial(_block, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(carry, lp):
        return block(carry, lp), None

    x, _ = lax.scan(body, x, params["blocks"])
    x = _layer_norm(x[:, 0], params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = x @ params["head"]["kernel"].astype(cfg.dtype) \
        + params["head"]["bias"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: ViTConfig) -> jax.Array:
    """batch: {"images": (B,H,W,C), "labels": (B,) int32} → mean CE."""
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(
        logp, batch["labels"][:, None], -1).mean()


def param_count_analytic(cfg: ViTConfig) -> int:
    E, L, M = cfg.n_embd, cfg.n_layer, cfg.mlp_ratio * cfg.n_embd
    per_layer = 4 * E * E + 4 * E + 2 * E * M + E + M + 4 * E
    stem = (cfg.patch_size ** 2 * 3 + 1) * E + (cfg.num_patches + 1) * E + E
    head = (E + 1) * cfg.num_classes + 2 * E
    return stem + L * per_layer + head
