"""Shared helpers for the model zoo (single source for init/count logic)."""

from __future__ import annotations

from typing import Any

import jax


def normal_init(key: jax.Array, shape, dtype, scale: float = 0.02):
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def param_count(params: Any) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
