"""ResNet family (ResNet-50 flagship) — baseline #2 (JaxTrainer ImageNet).

The reference framework ships no models; its ResNet-50 benchmark is
torchvision inside Train workers (reference: ``python/ray/train/``
examples).  This is a TPU-first reimplementation, not a torch port:

- NHWC layout end to end (TPU convolutions are NHWC-native; torch is NCHW).
- GroupNorm + weight standardization instead of BatchNorm: BN's
  cross-replica batch-stat sync is a distributed-training liability (an
  extra all-reduce per layer and a source of DP-degree-dependent numerics);
  GN+WS is the public Big-Transfer (BiT) recipe that matches BN accuracy
  while keeping the train step a pure function of (params, batch) — which is
  what lets the whole step live in one jit.
- bf16 activations / f32 params; convs hit the MXU.
- Pure pytree params, stacked per stage where shapes agree (scan-friendly).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    gn_groups: int = 32
    remat: bool = False


def resnet18() -> ResNetConfig:
    return ResNetConfig(stage_sizes=(2, 2, 2, 2))


def resnet50() -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3))


def resnet101() -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 23, 3))


def tiny(num_classes: int = 10) -> ResNetConfig:
    """CIFAR-scale config for tests."""
    return ResNetConfig(stage_sizes=(1, 1), width=16, num_classes=num_classes,
                        gn_groups=8)


PRESETS = {"resnet18": resnet18, "resnet50": resnet50,
           "resnet101": resnet101, "tiny": tiny}


# ------------------------------------------------------------------- params
def _conv_init(key, shape, dtype):
    # shape = (kh, kw, cin, cout); He fan-out init (matches BiT)
    fan_out = shape[0] * shape[1] * shape[3]
    std = math.sqrt(2.0 / fan_out)
    return (jax.random.normal(key, shape) * std).astype(dtype)


def _norm_params(c, pd):
    return {"scale": jnp.ones((c,), pd), "bias": jnp.zeros((c,), pd)}


def _bottleneck_params(key, cin, cmid, pd, stride):
    ks = jax.random.split(key, 4)
    cout = cmid * 4
    p = {
        "conv1": _conv_init(ks[0], (1, 1, cin, cmid), pd),
        "gn1": _norm_params(cmid, pd),
        "conv2": _conv_init(ks[1], (3, 3, cmid, cmid), pd),
        "gn2": _norm_params(cmid, pd),
        "conv3": _conv_init(ks[2], (1, 1, cmid, cout), pd),
        "gn3": _norm_params(cout, pd),
    }
    if stride != 1 or cin != cout:
        p["proj"] = _conv_init(ks[3], (1, 1, cin, cout), pd)
        p["gn_proj"] = _norm_params(cout, pd)
    return p


def init_params(rng: jax.Array, cfg: ResNetConfig) -> Params:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, 4 + sum(cfg.stage_sizes)))
    params: Params = {
        "stem": {"conv": _conv_init(next(keys), (7, 7, 3, cfg.width), pd),
                 "gn": _norm_params(cfg.width, pd)},
    }
    cin = cfg.width
    for si, n_blocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** si)
        blocks = []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            blocks.append(_bottleneck_params(next(keys), cin, cmid, pd, stride))
            cin = cmid * 4
        params[f"stage{si}"] = blocks
    params["head"] = {
        "kernel": jnp.zeros((cin, cfg.num_classes), pd),  # zero-init head
        "bias": jnp.zeros((cfg.num_classes,), pd),
    }
    return params


# ------------------------------------------------------------------ forward
def _standardize(w):
    # weight standardization over (kh, kw, cin)
    w32 = w.astype(jnp.float32)
    mu = w32.mean((0, 1, 2), keepdims=True)
    var = w32.var((0, 1, 2), keepdims=True)
    return ((w32 - mu) * lax.rsqrt(var + 1e-10)).astype(w.dtype)


def _conv(x, w, stride=1, ws=True):
    w = _standardize(w) if ws else w
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _group_norm(x, scale, bias, groups):
    B, H, W, C = x.shape
    g = min(groups, C)
    x32 = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mu = x32.mean((1, 2, 4), keepdims=True)
    var = x32.var((1, 2, 4), keepdims=True)
    y = ((x32 - mu) * lax.rsqrt(var + 1e-5)).reshape(B, H, W, C)
    return (y * scale + bias).astype(x.dtype)


def _bottleneck(x, bp, cfg: ResNetConfig, stride: int):
    norm = partial(_group_norm, groups=cfg.gn_groups)
    r = x
    y = jax.nn.relu(norm(_conv(x, bp["conv1"]), **bp["gn1"]))
    y = jax.nn.relu(norm(_conv(y, bp["conv2"], stride), **bp["gn2"]))
    y = norm(_conv(y, bp["conv3"]), **bp["gn3"])
    if "proj" in bp:
        r = norm(_conv(x, bp["proj"], stride), **bp["gn_proj"])
    return jax.nn.relu(r + y)


def forward(params: Params, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images (B, H, W, 3) float → logits (B, num_classes) f32."""
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"]["conv"], stride=2)
    x = jax.nn.relu(_group_norm(x, groups=cfg.gn_groups,
                                **params["stem"]["gn"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for si, n_blocks in enumerate(cfg.stage_sizes):
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            block = partial(_bottleneck, cfg=cfg, stride=stride)
            if cfg.remat:
                block = jax.checkpoint(block)
            x = block(x, params[f"stage{si}"][bi])
    x = x.mean((1, 2))  # global average pool
    logits = x.astype(jnp.float32) @ params["head"]["kernel"].astype(jnp.float32) \
        + params["head"]["bias"].astype(jnp.float32)
    return logits


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: ResNetConfig, label_smoothing: float = 0.0) -> jax.Array:
    """batch: {"images": (B,H,W,3), "labels": (B,) int32}."""
    logits = forward(params, batch["images"], cfg)
    labels = batch["labels"]
    n = logits.shape[-1]
    targets = jax.nn.one_hot(labels, n)
    if label_smoothing > 0:
        targets = targets * (1 - label_smoothing) + label_smoothing / n
    logp = jax.nn.log_softmax(logits)
    return -(targets * logp).sum(-1).mean()


def accuracy(params: Params, batch: Dict[str, jax.Array],
             cfg: ResNetConfig) -> jax.Array:
    logits = forward(params, batch["images"], cfg)
    return (logits.argmax(-1) == batch["labels"]).mean()


# Sharding rules: convs fsdp-sharded on cout (ZeRO-3 style), head dense
# sharded like an MLP output; everything else replicated (ResNet is small —
# DP/FSDP dominate, TP does not pay off).
from jax.sharding import PartitionSpec as _P  # noqa: E402

RESNET_RULES = [
    (r".*stem/conv$",   _P(None, None, None, "fsdp")),
    (r".*conv[123]$",   _P(None, None, None, "fsdp")),
    (r".*proj$",        _P(None, None, None, "fsdp")),
    (r".*head/kernel$", _P("fsdp", "tensor")),
    (r".*", _P(None)),
]


from ray_tpu.models._common import param_count  # noqa: E402,F401
