"""T5 encoder-decoder family (t5.1.1 recipe) — seq2seq on TPU.

Reference contrast: the reference ships no models; encoder-decoder
workloads run HuggingFace-on-torch inside its Train workers.  TPU-first
design notes (T5 1.1):

- RMSNorm (no bias, pre-LN), gated-GELU feed-forward, no biases in any
  projection, untied LM head — the t5.1.1 improvements.
- Relative position BUCKETS shared across layers (one (heads, q, k) bias
  tensor per stack, computed once per shape and added to every layer's
  attention logits — T5's weight-sharing scheme).
- Encoder and decoder are each stacked-layer ``lax.scan`` stacks (one
  block compile each); the decoder carries self-attention (causal +
  relative bias) and cross-attention (no bias) per layer.
- bf16 activations / f32 params; f32 norms and softmax.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models._common import normal_init as _init

Params = Dict[str, Any]


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    n_embd: int = 768            # d_model
    d_ff: int = 2048             # t5.1.1-base
    n_layer: int = 12            # per stack
    n_head: int = 12
    head_dim: int = 64
    rel_buckets: int = 32
    rel_max_distance: int = 128
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False


def t5_base() -> T5Config:    # ~250M
    return T5Config()


def t5_large() -> T5Config:   # ~780M
    return T5Config(n_embd=1024, d_ff=2816, n_layer=24, n_head=16)


def tiny(vocab: int = 256) -> T5Config:
    return T5Config(vocab_size=vocab, n_embd=64, d_ff=128, n_layer=2,
                    n_head=4, head_dim=16, rel_buckets=8,
                    rel_max_distance=32)


PRESETS = {"t5-base": t5_base, "t5-large": t5_large, "tiny": tiny}


# ------------------------------------------------------------------- params
def _stack_params(k, cfg: T5Config, cross: bool) -> Params:
    pd = cfg.param_dtype
    E, L, H, D, F = (cfg.n_embd, cfg.n_layer, cfg.n_head, cfg.head_dim,
                     cfg.d_ff)

    def stack(shape, scale=None):
        s = 0.02 if scale is None else scale
        return jnp.stack([_init(next(k), shape, pd, s) for _ in range(L)])

    p = {
        "ln_attn": {"scale": jnp.ones((L, E), pd)},
        "attn_q": stack((E, H * D), (E * D) ** -0.5),
        "attn_k": stack((E, H * D), E ** -0.5),
        "attn_v": stack((E, H * D), E ** -0.5),
        "attn_o": stack((H * D, E), (H * D) ** -0.5),
        "ln_mlp": {"scale": jnp.ones((L, E), pd)},
        "wi_0": stack((E, F), E ** -0.5),   # gated gelu: gate
        "wi_1": stack((E, F), E ** -0.5),   # gated gelu: value
        "wo": stack((F, E), F ** -0.5),
    }
    if cross:
        p["ln_cross"] = {"scale": jnp.ones((L, E), pd)}
        p["cross_q"] = stack((E, H * D), (E * D) ** -0.5)
        p["cross_k"] = stack((E, H * D), E ** -0.5)
        p["cross_v"] = stack((E, H * D), E ** -0.5)
        p["cross_o"] = stack((H * D, E), (H * D) ** -0.5)
    return p


def init_params(rng: jax.Array, cfg: T5Config) -> Params:
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 8 + 24 * cfg.n_layer))
    return {
        "shared_embed": _init(next(k), (cfg.vocab_size, cfg.n_embd), pd,
                              1.0),
        "enc_rel_bias": _init(next(k), (cfg.rel_buckets, cfg.n_head), pd),
        "dec_rel_bias": _init(next(k), (cfg.rel_buckets, cfg.n_head), pd),
        "encoder": _stack_params(k, cfg, cross=False),
        "decoder": _stack_params(k, cfg, cross=True),
        "enc_ln_f": {"scale": jnp.ones((cfg.n_embd,), pd)},
        "dec_ln_f": {"scale": jnp.ones((cfg.n_embd,), pd)},
        "lm_head": _init(next(k), (cfg.n_embd, cfg.vocab_size), pd,
                         cfg.n_embd ** -0.5),
    }


# ------------------------------------------------------------------ forward
def _rms_norm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = (x32 * x32).mean(-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _relative_buckets(rel: jax.Array, num_buckets: int, max_dist: int,
                      bidirectional: bool) -> jax.Array:
    """T5's log-bucketed relative positions (reference recipe)."""
    ret = jnp.zeros_like(rel)
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_dist / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _rel_bias(table: jax.Array, q_len: int, k_len: int, cfg: T5Config,
              bidirectional: bool) -> jax.Array:
    """(buckets, H) table → (1, H, q, k) bias, f32."""
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _relative_buckets(mem - ctx, cfg.rel_buckets,
                                cfg.rel_max_distance, bidirectional)
    bias = table.astype(jnp.float32)[buckets]        # (q, k, H)
    return bias.transpose(2, 0, 1)[None]


def _attn(q, k, v, bias, cfg: T5Config):
    """(B,T,H*D)×3 + (1|B,H,q,k) bias → (B,q,H*D).  T5 does NOT scale
    logits by sqrt(D) (folded into init)."""
    B, Tq = q.shape[:2]
    Tk = k.shape[1]
    H, D = cfg.n_head, cfg.head_dim
    q = q.reshape(B, Tq, H, D)
    k = k.reshape(B, Tk, H, D)
    v = v.reshape(B, Tk, H, D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    probs = jax.nn.softmax(logits, -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, Tq, H * D)


def _ff(x, lp, cfg: T5Config):
    h = _rms_norm(x, lp["ln_mlp"]["scale"])
    gate = jax.nn.gelu(h @ lp["wi_0"].astype(cfg.dtype), approximate=True)
    up = h @ lp["wi_1"].astype(cfg.dtype)
    return x + (gate * up) @ lp["wo"].astype(cfg.dtype)


def _enc_block(x, lp, bias, cfg: T5Config):
    h = _rms_norm(x, lp["ln_attn"]["scale"])
    a = _attn(h @ lp["attn_q"].astype(cfg.dtype),
              h @ lp["attn_k"].astype(cfg.dtype),
              h @ lp["attn_v"].astype(cfg.dtype), bias, cfg)
    x = x + a @ lp["attn_o"].astype(cfg.dtype)
    return _ff(x, lp, cfg)


def _dec_block(x, lp, enc, self_bias, cfg: T5Config):
    h = _rms_norm(x, lp["ln_attn"]["scale"])
    a = _attn(h @ lp["attn_q"].astype(cfg.dtype),
              h @ lp["attn_k"].astype(cfg.dtype),
              h @ lp["attn_v"].astype(cfg.dtype), self_bias, cfg)
    x = x + a @ lp["attn_o"].astype(cfg.dtype)
    h = _rms_norm(x, lp["ln_cross"]["scale"])
    a = _attn(h @ lp["cross_q"].astype(cfg.dtype),
              enc @ lp["cross_k"].astype(cfg.dtype),
              enc @ lp["cross_v"].astype(cfg.dtype), None, cfg)
    x = x + a @ lp["cross_o"].astype(cfg.dtype)
    return _ff(x, lp, cfg)


def encode(params: Params, input_ids: jax.Array, cfg: T5Config) -> jax.Array:
    x = params["shared_embed"].astype(cfg.dtype)[input_ids]
    T = input_ids.shape[1]
    bias = _rel_bias(params["enc_rel_bias"], T, T, cfg, bidirectional=True)
    block = partial(_enc_block, bias=bias, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)
    x, _ = lax.scan(lambda c, lp: (block(c, lp), None), x,
                    params["encoder"])
    return _rms_norm(x, params["enc_ln_f"]["scale"])


def decode(params: Params, decoder_ids: jax.Array, enc: jax.Array,
           cfg: T5Config) -> jax.Array:
    x = params["shared_embed"].astype(cfg.dtype)[decoder_ids]
    T = decoder_ids.shape[1]
    bias = _rel_bias(params["dec_rel_bias"], T, T, cfg, bidirectional=False)
    causal = jnp.tril(jnp.ones((T, T), bool))
    bias = jnp.where(causal[None, None], bias, jnp.float32(-1e9))
    block = partial(_dec_block, enc=enc, self_bias=bias, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)
    x, _ = lax.scan(lambda c, lp: (block(c, lp), None), x,
                    params["decoder"])
    x = _rms_norm(x, params["dec_ln_f"]["scale"])
    return jnp.einsum("bte,ev->btv",
                      x, params["lm_head"].astype(cfg.dtype)
                      ).astype(jnp.float32)


def forward(params: Params, input_ids: jax.Array, decoder_ids: jax.Array,
            cfg: T5Config) -> jax.Array:
    """(B,S) encoder ids + (B,T) decoder ids → (B,T,vocab) f32 logits."""
    return decode(params, decoder_ids, encode(params, input_ids, cfg), cfg)


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: T5Config) -> jax.Array:
    """batch: {"inputs": (B,S), "decoder_inputs": (B,T), "targets": (B,T)}
    → mean teacher-forced CE."""
    logits = forward(params, batch["inputs"], batch["decoder_inputs"], cfg)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(
        logp, batch["targets"][..., None], -1).mean()


def param_count_analytic(cfg: T5Config) -> int:
    E, L, HD, F = (cfg.n_embd, cfg.n_layer, cfg.n_head * cfg.head_dim,
                   cfg.d_ff)
    enc_layer = 3 * E * HD + HD * E + 2 * E * F + F * E + 2 * E
    dec_layer = enc_layer + 3 * E * HD + HD * E + E
    shared = cfg.vocab_size * E * 2 + 2 * cfg.rel_buckets * cfg.n_head + 2 * E
    return shared + L * (enc_layer + dec_layer)
