"""``@ray_tpu.remote`` classes: ActorClass / ActorHandle / ActorMethod.

Reference: ``python/ray/actor.py`` (SURVEY.md §2.3, §3.3).  Semantics kept:
``Cls.remote(...)`` returns a handle immediately (creation is async);
``handle.method.remote(...)`` returns ObjectRef(s) with per-handle ordering;
``max_restarts``/``max_task_retries`` drive the GCS actor FSM; named actors
via ``name=`` + ``ray_tpu.get_actor``; handles are serializable and can be
passed to tasks/actors.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as _worker
from ray_tpu.util.scheduling_strategies import strategy_to_spec

_ACTOR_DEFAULTS = dict(
    # num_cpus=None means the reference's default actor semantics: 1 CPU
    # for creation SCHEDULING, 0 held while alive.  Explicit num_cpus /
    # num_tpus / resources are held for the actor's lifetime.
    num_cpus=None, num_tpus=0, resources=None, max_restarts=0,
    max_task_retries=0, max_concurrency=1, name=None, namespace="default",
    lifetime=None, get_if_exists=False, scheduling_strategy=None,
    runtime_env=None)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args: Any, **kwargs: Any):
        h = self._handle
        w = _worker.global_worker()
        refs = w.call_actor(
            h._actor_id, self._method_name, args, kwargs,
            num_returns=self._num_returns,
            max_task_retries=h._max_task_retries)
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns: int = 1, **_ignored):
        return ActorMethod(self._handle, self._method_name, num_returns)


class ActorHandle:
    def __init__(self, actor_id: str, method_meta: Dict[str, dict],
                 max_task_retries: int = 0, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._method_meta = method_meta
        self._max_task_retries = max_task_retries
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        meta = self._method_meta.get(name)
        if meta is None and name not in ("__ray_ready__", "__ray_terminate__"):
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}")
        return ActorMethod(self, name, (meta or {}).get("num_returns", 1))

    @property
    def __ray_ready__(self) -> ActorMethod:
        return ActorMethod(self, "__ray_ready__", 1)

    @property
    def __ray_terminate__(self) -> ActorMethod:
        return ActorMethod(self, "__ray_terminate__", 1)

    @property
    def __ray_apply__(self) -> ActorMethod:
        """Run ``fn(instance, *args, **kwargs)`` inside the actor process.

        Reference: ``ActorHandle.__ray_call__`` — the generic escape hatch
        used by ``ray.util.collective`` setup and Train's worker group.
        """
        return ActorMethod(self, "__ray_apply__", 1)

    # Reference-compatible alias.
    __ray_call__ = __ray_apply__

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id[:8]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_meta,
                              self._max_task_retries, self._class_name))


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = {**_ACTOR_DEFAULTS, **(options or {})}
        functools.update_wrapper(self, cls, updated=[])

    def remote(self, *args: Any, **kwargs: Any) -> ActorHandle:
        o = self._options
        w = _worker.global_worker()
        hold = (o["num_cpus"] is not None or bool(o["num_tpus"])
                or bool(o["resources"]))
        info = w.create_actor(
            self._cls, args, kwargs,
            hold_resources=hold,
            num_cpus=1 if o["num_cpus"] is None else o["num_cpus"],
            num_tpus=o["num_tpus"],
            resources=o["resources"], max_restarts=o["max_restarts"],
            max_task_retries=o["max_task_retries"],
            max_concurrency=o["max_concurrency"],
            name=o["name"], namespace=o["namespace"],
            detached=(o["lifetime"] == "detached"),
            get_if_exists=o["get_if_exists"],
            scheduling_strategy=strategy_to_spec(o["scheduling_strategy"]),
            runtime_env=o["runtime_env"])
        return ActorHandle(info["actor_id"], info["method_meta"],
                           o["max_task_retries"], self._cls.__name__)

    def options(self, **overrides: Any) -> "ActorClass":
        merged = {**self._options}
        for k, v in overrides.items():
            if k == "num_gpus":
                k = "num_tpus"
            if k not in _ACTOR_DEFAULTS:
                raise ValueError(f"unknown actor option {k!r}")
            merged[k] = v
        return ActorClass(self._cls, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            "directly; use .remote()")

    @property
    def cls(self) -> type:
        return self._cls


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = _worker.global_worker()
    resp = w.rpc("get_actor_by_name", name=name, namespace=namespace)
    return ActorHandle(resp["actor_id"], resp.get("method_meta") or {},
                       0, name)
