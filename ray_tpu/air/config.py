"""Run/scaling/failure/checkpoint configuration.

Reference: ``python/ray/air/config.py`` (SURVEY.md §2.5/§3.4).  The TPU
extension (SURVEY.md §2.4 "elastic/advanced placement") is that
``ScalingConfig`` can request *topology-shaped* reservations — a pod slice
(``topology="v4-32"``) leased atomically to the worker group — instead of
per-worker chip counts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.topology import slice_spec


@dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    num_workers: data-parallel worker count (one actor per TPU host when
        ``topology`` is set — all hosts of a slice are leased together).
    use_tpu: workers get TPU chips (reference: ``use_gpu``; accepted as an
        alias kwarg).
    resources_per_worker: extra custom resources per worker.
    topology: pod-slice topology string (e.g. "v4-8"); when set, the
        placement group is STRICT_PACK over one ICI domain and
        ``num_workers`` defaults to the slice's host count.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    topology: Optional[str] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if self.topology is not None:
            topo = slice_spec(self.topology)
            self.use_tpu = True
            if self.num_workers in (0, 1) and topo.num_hosts > 1:
                self.num_workers = topo.num_hosts
            self.placement_strategy = "STRICT_PACK"

    @property
    def num_tpus_per_worker(self) -> float:
        if not self.use_tpu:
            return 0.0
        if self.topology is not None:
            topo = slice_spec(self.topology)
            return topo.chips_per_host
        return float((self.resources_per_worker or {}).get("TPU", 1.0))

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res["TPU"] = self.num_tpus_per_worker
        res.pop("GPU", None)
        return res

    def as_placement_group_factory(self):
        from ray_tpu.util.placement_group import placement_group
        bundles = [self.bundle() for _ in range(self.num_workers)]
        return lambda: placement_group(bundles,
                                       strategy=self.placement_strategy)


@dataclass
class FailureConfig:
    """Reference: ``ray.air.FailureConfig`` — worker-group restarts from the
    last checkpoint, up to ``max_failures`` (-1 = unlimited)."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: ``ray.air.CheckpointConfig`` — retention policy."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # Tune stop criteria: {"metric_or_time_attr": bound} — stop a trial
    # once attribute >= bound (reference: ``air.RunConfig(stop=...)``)
    stop: Optional[Dict[str, Any]] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")
