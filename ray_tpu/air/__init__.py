"""AIR umbrella: shared config/result/checkpoint types.

Reference: ``python/ray/air/`` (SURVEY.md §2.5) — ``Checkpoint``, ``Result``,
``ScalingConfig``/``RunConfig``/``FailureConfig``/``CheckpointConfig`` shared
by Train and Tune.
"""

from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig,
)
from ray_tpu.train._checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.result import Result  # noqa: F401
