"""Dataset: lazy, distributed, block-based data.

Reference: ``python/ray/data/dataset.py`` (SURVEY.md §2.5).  A Dataset is a
plan (stage list) over source blocks; execution streams block refs through
fused map waves with backpressure (see _internal/execution.py).  Blocks are
dicts of numpy columns in the shm object store — ``iter_device_batches``
stages them into TPU HBM with double buffering (the north-star ingest path,
SURVEY.md §2.4).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data._internal.execution import (
    AllToAllStage, MapStage, Stage, stream_refs)
from ray_tpu.data.block import (
    Block, BlockAccessor, block_from_rows, concat_blocks)
from ray_tpu.data.context import DataContext


def _batched_map_fn(fn: Callable, batch_size: Optional[int],
                    batch_format: str) -> Callable[[Block], Block]:
    # block_format is captured at DATASET-BUILD time (driver context) —
    # the closure executes in workers, whose DataContext singleton is a
    # fresh default
    blk_fmt = DataContext.get_current().block_format

    def apply(block: Block) -> Block:
        acc = BlockAccessor(block)
        rows = acc.num_rows()
        bs = batch_size or max(rows, 1)
        outs = []
        for s in range(0, max(rows, 1), bs):
            if rows == 0:
                break
            batch = BlockAccessor(acc.slice(s, min(s + bs, rows))) \
                .to_batch(batch_format)
            out = fn(batch)
            outs.append(BlockAccessor.batch_to_block(out, blk_fmt))
        return concat_blocks(outs, blk_fmt)
    return apply


def _row_map_fn(fn: Callable) -> Callable[[Block], Block]:
    blk_fmt = DataContext.get_current().block_format

    def apply(block: Block) -> Block:
        rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
        return block_from_rows(rows, blk_fmt)
    return apply


class Dataset:
    def __init__(self, stages: List[Stage],
                 input_refs: Optional[List[Any]] = None):
        self._stages = stages
        self._input_refs = input_refs
        self._cached_refs: Optional[List[Any]] = None

    # ------------------------------------------------------------ plumbing
    def _with_stage(self, stage: Stage) -> "Dataset":
        if self._cached_refs is not None:
            return Dataset([stage], input_refs=list(self._cached_refs))
        return Dataset(self._stages + [stage], self._input_refs)

    def _iter_refs(self) -> Iterator[Any]:
        if self._cached_refs is not None:
            yield from self._cached_refs
            return
        yield from stream_refs(self._stages, self._input_refs)

    def materialize(self) -> "Dataset":
        """Execute and pin all blocks (reference: ``Dataset.materialize``)."""
        if self._cached_refs is None:
            self._cached_refs = list(self._iter_refs())
        return self

    # --------------------------------------------------------- transforms
    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with_stage(MapStage(_row_map_fn(fn), "Map"))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    num_cpus: Optional[float] = None,
                    fuse: bool = True,
                    **_compat: Any) -> "Dataset":
        """``num_cpus``/``fuse=False`` make this stage its own pipeline
        operator (its tasks overlap upstream ingest instead of fusing
        into it — reference: streaming executor operator boundaries)."""
        st = MapStage(_batched_map_fn(fn, batch_size, batch_format),
                      "MapBatches")
        if num_cpus is not None or not fuse:
            st.fusable = False
            st.remote_args = {} if num_cpus is None \
                else {"num_cpus": num_cpus}
        return self._with_stage(st)

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        blk_fmt = DataContext.get_current().block_format

        def apply(block: Block) -> Block:
            rows: List[Dict] = []
            for r in BlockAccessor(block).iter_rows():
                rows.extend(fn(r))
            return block_from_rows(rows, blk_fmt)
        return self._with_stage(MapStage(apply, "FlatMap"))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = np.fromiter((bool(fn(r)) for r in acc.iter_rows()),
                               dtype=bool, count=acc.num_rows())
            return acc.take_idx(np.nonzero(keep)[0])
        return self._with_stage(MapStage(apply, "Filter"))

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            vals = [fn(batch) for batch in [acc.to_batch("numpy")]]
            return acc.with_column(name, vals[0])
        return self._with_stage(MapStage(apply, "AddColumn"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def apply(block: Block) -> Block:
            return BlockAccessor(block).drop(cols)
        return self._with_stage(MapStage(apply, "DropColumns"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def apply(block: Block) -> Block:
            return BlockAccessor(block).select(cols)
        return self._with_stage(MapStage(apply, "SelectColumns"))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def apply(block: Block) -> Block:
            return BlockAccessor(block).rename(mapping)
        return self._with_stage(MapStage(apply, "RenameColumns"))

    # ----------------------------------------------------------- shuffles
    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_stage(
            AllToAllStage("repartition", num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with_stage(AllToAllStage("random_shuffle", seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        # sample boundaries (reference: sort sampling in shuffle planner)
        self.materialize()
        samples: List[np.ndarray] = []
        for ref in self._cached_refs:
            block = ray_tpu.get(ref)
            col = BlockAccessor(block).get_column(key)
            if col is not None and len(col):
                samples.append(np.random.default_rng(0).choice(
                    col, size=min(100, len(col)), replace=False)
                    if len(col) > 100 else col)
        n_out = max(1, len(self._cached_refs))
        if samples:
            allv = np.sort(np.concatenate(samples))
            qs = np.linspace(0, 1, n_out + 1)[1:-1]
            boundaries = [allv[int(q * (len(allv) - 1))] for q in qs]
        else:
            boundaries = []
        ds = self._with_stage(AllToAllStage(
            "sort", key=key, descending=descending, boundaries=boundaries,
            num_blocks=n_out))
        if descending:
            # partitions come back ascending-ordered; reverse block order
            ds.materialize()
            ds._cached_refs = list(reversed(ds._cached_refs))
        return ds

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # -------------------------------------------------------- combination
    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self.materialize()._cached_refs)
        for o in others:
            refs.extend(o.materialize()._cached_refs)
        return Dataset([], input_refs=refs)

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.materialize()._cached_refs
        right = other.materialize()._cached_refs

        @ray_tpu.remote
        def _rows(b: Block) -> int:
            return BlockAccessor(b).num_rows()

        left_counts = ray_tpu.get([_rows.remote(r) for r in left])
        right_counts = ray_tpu.get([_rows.remote(r) for r in right])
        if sum(left_counts) != sum(right_counts):
            raise ValueError("zip requires datasets with equal row counts")

        @ray_tpu.remote
        def merge(a: Block, spans, *right_blocks) -> Block:
            # spans: [(right_block_idx, lo, hi)] covering a's row range —
            # only the needed right blocks ship to this task, never the
            # whole right dataset to the driver
            pieces = [BlockAccessor(right_blocks[i]).slice(lo, hi)
                      for i, (_, lo, hi) in enumerate(spans)]
            return BlockAccessor(a).merge(concat_blocks(pieces))

        # map each left block's global row range onto right-block spans
        r_starts = np.concatenate([[0], np.cumsum(right_counts)])
        refs, pos = [], 0
        for lref, cnt in zip(left, left_counts):
            lo_g, hi_g = pos, pos + cnt
            pos = hi_g
            spans, blocks = [], []
            for j, (s, e) in enumerate(zip(r_starts[:-1], r_starts[1:])):
                if e <= lo_g or s >= hi_g:
                    continue
                spans.append((j, int(max(lo_g, s) - s), int(min(hi_g, e) - s)))
                blocks.append(right[j])
            refs.append(merge.remote(lref, spans, *blocks))
        return Dataset([], input_refs=refs)

    def limit(self, n: int) -> "Dataset":
        refs_out: List[Any] = []
        taken = 0
        for ref in self._iter_refs():
            block = ray_tpu.get(ref)
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            if taken + rows <= n:
                refs_out.append(ref)
                taken += rows
            else:
                refs_out.append(ray_tpu.put(acc.slice(0, n - taken)))
                taken = n
            if taken >= n:
                break
        return Dataset([], input_refs=refs_out)

    # ------------------------------------------------------------- splits
    def split(self, n: int, *, equal: bool = False,
              locality_hints: Optional[List[Any]] = None) -> List["Dataset"]:
        """Reference: ``Dataset.split(n, locality_hints=workers)`` — the
        per-worker sharding primitive Train uses (SURVEY.md §3.4)."""
        self.materialize()
        refs = list(self._cached_refs)
        if not equal:
            shards = [refs[i::n] for i in range(n)]
            return [Dataset([], input_refs=s) for s in shards]
        blocks = [ray_tpu.get(r) for r in refs]
        whole = concat_blocks(blocks)
        acc = BlockAccessor(whole)
        rows = acc.num_rows()
        bounds = np.linspace(0, rows, n + 1).astype(int)
        return [Dataset([], input_refs=[
            ray_tpu.put(acc.slice(bounds[i], bounds[i + 1]))])
            for i in range(n)]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        whole = BlockAccessor(concat_blocks(
            [ray_tpu.get(r) for r in self.materialize()._cached_refs]))
        cuts = [0] + list(indices) + [whole.num_rows()]
        return [Dataset([], input_refs=[ray_tpu.put(
            whole.slice(cuts[i], cuts[i + 1]))])
            for i in range(len(cuts) - 1)]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        total = ds.count()
        cut = int(total * (1 - test_size))
        parts = ds.split_at_indices([cut])
        return parts[0], parts[1]

    # -------------------------------------------------------- consumption
    def count(self) -> int:
        @ray_tpu.remote
        def _count(b: Block) -> int:
            return BlockAccessor(b).num_rows()
        return sum(ray_tpu.get([_count.remote(r) for r in self._iter_refs()]))

    # global aggregates (reference: Dataset.sum/min/max/mean/std/unique).
    # Each block reduces to a tiny partial INSIDE its task; only O(blocks)
    # scalars (or unique sets) cross the object store, never whole columns.
    def _partials(self, on: str) -> List[Optional[tuple]]:
        """Per-block (n, mean, M2, min, max) — mean/M2 are the Welford
        moments (None for non-numeric columns), mergeable without the
        catastrophic cancellation of raw sum-of-squares."""
        @ray_tpu.remote
        def _part(b: Block):
            raw = np.asarray(b[on])
            if raw.size == 0:
                return None
            if raw.dtype.kind in "fiub":
                v = raw.astype(np.float64)
                mean = float(v.mean())
                m2 = float(((v - mean) ** 2).sum())
                # integers/bools: native accumulation is exact; floats:
                # float64 (a native float16/32 sum overflows/loses bits)
                s = raw.sum() if raw.dtype.kind in "iub" else float(v.sum())
            else:
                mean = m2 = s = None  # min/max stay lexicographic
            return (int(raw.size), mean, m2, raw.min(), raw.max(), s)
        return [p for p in ray_tpu.get(
            [_part.remote(r) for r in self._iter_refs()]) if p is not None]

    @staticmethod
    def _merged_moments(parts):
        """Chan et al. parallel merge of per-block (n, mean, M2)."""
        n, mean, m2 = 0, 0.0, 0.0
        for pn, pmean, pm2 in parts:
            if pmean is None:
                raise TypeError("numeric aggregate on non-numeric column")
            delta = pmean - mean
            tot = n + pn
            mean += delta * pn / tot
            m2 += pm2 + delta * delta * n * pn / tot
            n = tot
        return n, mean, m2

    def sum(self, on: str):
        parts = self._partials(on)
        if not parts:
            return None
        if parts[0][5] is None:
            raise TypeError("numeric aggregate on non-numeric column")
        return sum(p[5] for p in parts)

    def min(self, on: str):
        parts = self._partials(on)
        return min(p[3] for p in parts) if parts else None

    def max(self, on: str):
        parts = self._partials(on)
        return max(p[4] for p in parts) if parts else None

    def mean(self, on: str):
        parts = self._partials(on)
        if not parts:
            return None
        _, mean, _ = self._merged_moments([p[:3] for p in parts])
        return float(mean)

    def std(self, on: str, ddof: int = 1):
        parts = self._partials(on)
        if not parts:
            return None
        n, _, m2 = self._merged_moments([p[:3] for p in parts])
        if n <= ddof:
            return None
        return float(np.sqrt(m2 / (n - ddof)))

    def unique(self, on: str) -> List[Any]:
        @ray_tpu.remote
        def _uniq(b: Block) -> np.ndarray:
            return np.unique(np.asarray(b[on]))
        parts = [p for p in ray_tpu.get(
            [_uniq.remote(r) for r in self._iter_refs()]) if p.size]
        if not parts:
            return []
        return np.unique(np.concatenate(parts)).tolist()

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Row-wise Bernoulli sample (reference: Dataset.random_sample).

        Per-block randomness derives from (seed, block content signature)
        so equal-sized blocks draw independent masks; blocks with
        byte-identical content share a mask (deterministic by design)."""
        base = seed if seed is not None else np.random.SeedSequence().entropy

        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            import zlib
            edge = concat_blocks([acc.slice(0, 1), acc.slice(n - 1, n)])
            sig = zlib.crc32(n.to_bytes(8, "little") + b"".join(
                np.ascontiguousarray(np.asarray(v)).tobytes()
                if getattr(np.asarray(v), "dtype", None) != object
                else repr(list(v)).encode()
                for v in BlockAccessor(edge).to_batch("numpy").values()))
            rng = np.random.default_rng([int(base) % (2 ** 63), sig])
            mask = rng.random(n) < fraction
            return acc.take_idx(np.nonzero(mask)[0])
        return self._with_stage(MapStage(apply, "RandomSample"))

    def schema(self) -> Optional[Dict[str, Any]]:
        for ref in self._iter_refs():
            block = ray_tpu.get(ref)
            if BlockAccessor(block).num_rows():
                return BlockAccessor(block).schema()
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s) if s else None

    def num_blocks(self) -> int:
        return len(self.materialize()._cached_refs)

    def size_bytes(self) -> int:
        @ray_tpu.remote
        def _sz(b: Block) -> int:
            return BlockAccessor(b).size_bytes()
        return sum(ray_tpu.get([_sz.remote(r) for r in self._iter_refs()]))

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._iter_refs():
            for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return [r for ref in self._iter_refs()
                for r in BlockAccessor(ray_tpu.get(ref)).iter_rows()]

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._iter_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        """Streams: pulls blocks lazily (backpressure reaches the executor)."""
        carry: Optional[Block] = None
        for ref in self._iter_refs():
            block = ray_tpu.get(ref)
            if carry:
                block = concat_blocks([carry, block])
                carry = None
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            s = 0
            while rows - s >= batch_size:
                yield BlockAccessor(acc.slice(s, s + batch_size)) \
                    .to_batch(batch_format)
                s += batch_size
            if s < rows:
                carry = acc.slice(s, rows)
        if carry and not drop_last:
            yield BlockAccessor(carry).to_batch(batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           **kw) -> Iterator[Dict[str, Any]]:
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def iter_device_batches(self, *, batch_size: int = 256,
                            sharding: Optional[Any] = None,
                            prefetch: int = 2) -> Iterator[Any]:
        """Double-buffered host→HBM ingest (reference gap — SURVEY.md §2.4
        north star).  ``jax.device_put`` is async: by keeping ``prefetch``
        batches in flight, the H2D copy of batch k+1 overlaps step k."""
        import collections

        import jax
        q: collections.deque = collections.deque()
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            dev = {k: (jax.device_put(v, sharding) if v.dtype != object
                       else v) for k, v in batch.items()}
            q.append(dev)
            if len(q) > prefetch:
                yield q.popleft()
        while q:
            yield q.popleft()

    def to_pandas(self):
        return BlockAccessor(concat_blocks(
            [ray_tpu.get(r) for r in self._iter_refs()])).to_batch("pandas")

    # ---------------------------------------------------------------- IO
    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def _write(self, path: str, fmt: str) -> None:
        import os
        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _w(i: int, block: Block) -> None:
            import os

            acc = BlockAccessor(block)
            fname = os.path.join(path, f"part-{i:05d}.{fmt}")
            if fmt == "parquet":
                import pyarrow.parquet as pq
                pq.write_table(acc.to_batch("pyarrow"), fname)
            elif fmt == "csv":
                acc.to_batch("pandas").to_csv(fname, index=False)
            else:
                acc.to_batch("pandas").to_json(fname, orient="records",
                                               lines=True)
        ray_tpu.get([_w.remote(i, r)
                     for i, r in enumerate(self._iter_refs())])

    def __repr__(self) -> str:
        return f"Dataset(stages={len(self._stages)})"

    # reference-compat alias
    def fully_executed(self) -> "Dataset":
        return self.materialize()


class GroupedData:
    """Reference: ``python/ray/data/grouped_data.py``."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, specs) -> Dataset:
        return self._ds._with_stage(
            AllToAllStage("groupby", key=self._key, aggs=specs))

    def count(self) -> Dataset:
        return self._agg([("count", None, "count()")])

    def sum(self, on: str) -> Dataset:
        return self._agg([("sum", on, f"sum({on})")])

    def min(self, on: str) -> Dataset:
        return self._agg([("min", on, f"min({on})")])

    def max(self, on: str) -> Dataset:
        return self._agg([("max", on, f"max({on})")])

    def mean(self, on: str) -> Dataset:
        return self._agg([("mean", on, f"mean({on})")])

    def std(self, on: str) -> Dataset:
        return self._agg([("std", on, f"std({on})")])

    def aggregate(self, *specs) -> Dataset:
        """specs: (agg_name, on_col, out_name) triples."""
        return self._agg(list(specs))

    def map_groups(self, fn: Callable[[Dict[str, np.ndarray]], Any]) -> Dataset:
        key = self._key

        blk_fmt = DataContext.get_current().block_format

        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            if not acc.num_rows():
                return block
            keys = acc.get_column(key)
            outs = []
            for val in dict.fromkeys(keys.tolist()):  # ordered unique
                idx = np.nonzero(keys == val)[0]
                out = fn(BlockAccessor(acc.take_idx(idx)).to_batch("numpy"))
                outs.append(BlockAccessor.batch_to_block(out, blk_fmt))
            return concat_blocks(outs)

        # hash-partition so each group lands wholly in one block, then map
        return (self._ds
                ._with_stage(AllToAllStage("groupby_raw", key=key))
                ._with_stage(MapStage(apply, "MapGroups")))
