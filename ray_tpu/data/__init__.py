"""``ray_tpu.data`` — distributed datasets.

Reference: ``python/ray/data/`` (SURVEY.md §2.5): blocks in the object
store, lazy plans, streaming execution with backpressure, ``split`` for
per-worker shards, batch/device iteration for training ingest.
"""

from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import Dataset, GroupedData  # noqa: F401
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow, from_items, from_numpy, from_pandas, range, read_binary_files,
    read_csv, read_json, read_numpy, read_parquet, read_text,
)
