"""DataContext: execution knobs.

Reference: ``python/ray/data/context.py`` — a process-wide singleton of
execution options (block sizes, parallelism, backpressure limits).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class DataContext:
    # blocks created by read_*/from_* when override_num_blocks is unset
    default_parallelism: int = 8
    # streaming executor: max concurrently running block tasks per stage
    # (this is the backpressure bound — reference: resource-based limits)
    max_tasks_in_flight: int = 8
    target_max_block_size: int = 128 * 1024 * 1024
    # rows per batch when batch_size is unset in map_batches
    default_batch_size: int = 1024
    use_push_based_shuffle: bool = True
    # "numpy" (default: dict-of-ndarray blocks, zero-copy out of the shm
    # store and directly device_put-able) or "arrow" (pyarrow Table
    # blocks: zero-copy slice/concat, schema'd tabular path, conversion-
    # free parquet IO — the reference's block representation)
    block_format: str = "numpy"

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance
