"""Streaming execution of dataset plans.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py``
(SURVEY.md §2.5): operators pull blocks through map/shuffle stages with
backpressure.  Structure here:

- A plan is a stage list; chains of map-like stages are FUSED into one
  task per block (operator fusion — the reference does this in its
  optimizer), so a read→map→filter pipeline is one wave of tasks.
- ``stream_refs`` submits at most ``DataContext.max_tasks_in_flight``
  tasks and yields output refs as they complete: downstream consumers
  (``iter_batches``) pull lazily → bounded memory (backpressure).
- All-to-all stages (repartition / random_shuffle / sort / groupby) are
  barriers implemented as 2-phase map-reduce shuffles through the object
  store (the Exoshuffle pattern, SURVEY.md §6).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import (Block, BlockAccessor, concat_blocks)
from ray_tpu.data.context import DataContext


def _stable_hash(x: Any) -> int:
    """Cross-process-stable hash (Python's hash() is salted per process —
    shuffle partition tasks run in different workers)."""
    import zlib
    return zlib.crc32(repr(x).encode())


# ----------------------------------------------------------------- stages
class Stage:
    pass


class ReadStage(Stage):
    """Source: factories, each () -> Block."""

    def __init__(self, factories: Sequence[Callable[[], Block]], name="Read"):
        self.factories = list(factories)
        self.name = name


class MapStage(Stage):
    """fn: Block -> Block (fusable).  ``fusable=False`` (or custom
    ``remote_args``) makes this stage its own streaming-pipeline operator
    instead of fusing into its neighbors."""

    fusable = True
    remote_args: Optional[dict] = None

    def __init__(self, fn: Callable[[Block], Block], name="Map"):
        self.fn = fn
        self.name = name


class AllToAllStage(Stage):
    def __init__(self, kind: str, name: str = "", **kwargs):
        self.kind = kind
        self.kwargs = kwargs
        self.name = name or kind


# ------------------------------------------------------------ remote tasks
@ray_tpu.remote
def _source_task(factory_blob: bytes, fns_blob: bytes) -> Block:
    import cloudpickle
    factory = cloudpickle.loads(factory_blob)
    block = factory()
    for fn in cloudpickle.loads(fns_blob):
        block = fn(block)
    return block


@ray_tpu.remote
def _map_task(fns_blob: bytes, block: Block) -> Block:
    import cloudpickle
    for fn in cloudpickle.loads(fns_blob):
        block = fn(block)
    return block


@ray_tpu.remote
def _partition_task(fns_blob: bytes, part_fn_blob: bytes, n: int,
                    block: Block) -> List[Block]:
    """Shuffle phase 1: apply pending fns then split into n partitions."""
    import cloudpickle
    for fn in cloudpickle.loads(fns_blob):
        block = fn(block)
    part_fn = cloudpickle.loads(part_fn_blob)
    return part_fn(block, n)


@ray_tpu.remote
def _reduce_task(reduce_fn_blob: bytes, idx: int, *parts_lists) -> Block:
    """Shuffle phase 2: gather partition ``idx`` from every phase-1 output."""
    import cloudpickle
    reduce_fn = cloudpickle.loads(reduce_fn_blob)
    pieces = [pl[idx] for pl in parts_lists]
    return reduce_fn(pieces)


# ------------------------------------------------------------- scheduling
def _submit_capped(task_args: List[tuple], submit: Callable[..., Any],
                   cap: Optional[int] = None) -> Iterator[Any]:
    """Yield results refs in input order with ≤cap tasks in flight."""
    cap = cap or DataContext.get_current().max_tasks_in_flight
    refs: List[Any] = []
    idx = 0
    emitted = 0
    while emitted < len(task_args):
        while idx < len(task_args) and idx - emitted < cap:
            refs.append(submit(*task_args[idx]))
            idx += 1
        # wait for the head-of-line ref so ordering is preserved
        ray_tpu.wait([refs[emitted]], num_returns=1)
        yield refs[emitted]
        emitted += 1


def _fuse(stages: List[Stage]) -> List[Stage]:
    """Merge consecutive MapStages (and into a leading ReadStage)."""
    out: List[Stage] = []
    for st in stages:
        if isinstance(st, MapStage) and out and isinstance(out[-1], MapStage) \
                and st.fusable and out[-1].fusable:
            prev = out.pop()
            fns = getattr(prev, "_fns", [prev.fn]) + \
                getattr(st, "_fns", [st.fn])
            merged = MapStage(None, name=f"{prev.name}->{st.name}")
            merged._fns = fns
            out.append(merged)
        else:
            out.append(st)
    return out


def _stage_fns(st: MapStage) -> List[Callable]:
    return getattr(st, "_fns", [st.fn] if st.fn else [])


def stream_refs(stages: List[Stage],
                input_refs: Optional[List[Any]] = None) -> Iterator[Any]:
    """Execute the plan, yielding output block refs lazily.

    Runs the operator-pipelined streaming topology (streaming.py): every
    operator is concurrently in flight with bounded per-operator budgets;
    map chains stay fused into single tasks (the wave optimizer's win is
    preserved), non-fusable stages overlap their upstream."""
    from ray_tpu.data._internal.streaming import build_topology
    topo = build_topology(stages, input_refs)
    try:
        yield from topo
    finally:
        topo.stop()


def _run_wave(source: Optional[ReadStage], refs: Optional[List[Any]],
              fns_blob: bytes, ctx: DataContext) -> Iterator[Any]:
    import cloudpickle
    if source is not None:
        args = [(cloudpickle.dumps(f), fns_blob) for f in source.factories]
        yield from _submit_capped(
            args, lambda fb, mb: _source_task.remote(fb, mb),
            ctx.max_tasks_in_flight)
    else:
        args = [(fns_blob, r) for r in (refs or [])]
        yield from _submit_capped(
            args, lambda mb, r: _map_task.remote(mb, r),
            ctx.max_tasks_in_flight)


# --------------------------------------------------------------- shuffles
def _run_shuffle(st: AllToAllStage, input_refs: List[Any]) -> List[Any]:
    import cloudpickle
    from ray_tpu.data.context import DataContext
    kind = st.kind
    kw = st.kwargs
    n_out = kw.get("num_blocks") or max(1, len(input_refs))
    blk_fmt = DataContext.get_current().block_format

    if kind == "repartition":
        def part_fn(block: Block, n: int) -> List[Block]:
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            bounds = np.linspace(0, rows, n + 1).astype(int)
            return [acc.slice(bounds[k], bounds[k + 1]) for k in range(n)]

        def reduce_fn(pieces: List[Block], _f=blk_fmt) -> Block:
            return concat_blocks(pieces, _f)

    elif kind == "random_shuffle":
        seed = kw.get("seed")

        def part_fn(block: Block, n: int, _seed=seed) -> List[Block]:
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            rng = np.random.default_rng(_seed)
            assign = rng.integers(0, n, rows)
            return [acc.take_idx(np.nonzero(assign == k)[0])
                    for k in range(n)]

        def reduce_fn(pieces: List[Block], _seed=seed, _f=blk_fmt) -> Block:
            out = concat_blocks(pieces, _f)
            acc = BlockAccessor(out)
            rng = np.random.default_rng(_seed)
            perm = rng.permutation(acc.num_rows())
            return acc.take_idx(perm)

    elif kind == "sort":
        key = kw["key"]
        descending = kw.get("descending", False)
        bounds = kw["boundaries"]  # computed by caller from samples

        def part_fn(block: Block, n: int, _b=bounds, _k=key) -> List[Block]:
            acc = BlockAccessor(block)
            col = acc.get_column(_k)
            if col is None:
                return [acc.slice(0, 0) for _ in range(n)]
            assign = np.searchsorted(np.asarray(_b), col, side="right")
            return [acc.take_idx(np.nonzero(assign == k)[0])
                    for k in range(n)]

        def reduce_fn(pieces: List[Block], _k=key, _d=descending,
                      _f=blk_fmt) -> Block:
            out = concat_blocks(pieces, _f)
            acc = BlockAccessor(out)
            if not acc.num_rows():
                return out
            order = np.argsort(acc.get_column(_k), kind="stable")
            if _d:
                order = order[::-1]
            return BlockAccessor(out).take_idx(order)

    elif kind == "groupby_raw":
        key = kw["key"]

        def part_fn(block: Block, n: int, _k=key) -> List[Block]:
            acc = BlockAccessor(block)
            col = acc.get_column(_k)
            if col is None:
                return [acc.slice(0, 0) for _ in range(n)]
            h = np.array([_stable_hash(x) % n for x in col.tolist()])
            return [acc.take_idx(np.nonzero(h == k)[0]) for k in range(n)]

        def reduce_fn(pieces: List[Block], _f=blk_fmt) -> Block:
            return concat_blocks(pieces, _f)

    elif kind == "groupby":
        key = kw["key"]
        aggs = kw["aggs"]  # list of (agg_name, on_col, out_name)

        def part_fn(block: Block, n: int, _k=key) -> List[Block]:
            acc = BlockAccessor(block)
            col = acc.get_column(_k)
            if col is None:
                return [acc.slice(0, 0) for _ in range(n)]
            h = np.array([_stable_hash(x) % n for x in col.tolist()])
            return [acc.take_idx(np.nonzero(h == k)[0]) for k in range(n)]

        def reduce_fn(pieces: List[Block], _k=key, _aggs=aggs,
                      _f=blk_fmt) -> Block:
            from ray_tpu.data._internal.aggregate import apply_groupby
            return apply_groupby(concat_blocks(pieces, _f), _k, _aggs)

    else:
        raise ValueError(f"unknown shuffle kind {kind!r}")

    empty_fns = cloudpickle.dumps([])
    part_blob = cloudpickle.dumps(part_fn)
    parts_refs = [_partition_task.remote(empty_fns, part_blob, n_out, r)
                  for r in input_refs]
    red_blob = cloudpickle.dumps(reduce_fn)
    return [_reduce_task.remote(red_blob, k, *parts_refs)
            for k in range(n_out)]
