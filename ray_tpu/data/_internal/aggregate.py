"""Groupby aggregation kernels.

Reference: ``python/ray/data/_internal/planner/exchange/aggregate_*`` +
``ray.data.aggregate.AggregateFn`` family (Count/Sum/Min/Max/Mean/Std).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor

# (agg_name, on_col, out_name)
AggSpec = Tuple[str, str, str]

_KERNELS = {
    "count": lambda v: len(v),
    "sum": lambda v: np.sum(v),
    "min": lambda v: np.min(v),
    "max": lambda v: np.max(v),
    "mean": lambda v: np.mean(v),
    "std": lambda v: np.std(v, ddof=1) if len(v) > 1 else 0.0,
}


def apply_groupby(block: Block, key: str, aggs: List[AggSpec]) -> Block:
    acc = BlockAccessor(block)
    if not acc.num_rows():
        return {}
    # kernels are numpy reductions; pull ONLY the key + agg input columns
    # through the accessor (format-dispatching) so Arrow blocks aggregate
    # identically without converting unrelated columns (result block
    # stays numpy — the reduce output is small)
    needed = {key} | {on for _, on, _ in aggs if on}
    cols = {c: acc.get_column(c) for c in needed}
    missing = sorted(c for c, v in cols.items() if v is None)
    if missing:
        raise KeyError(
            f"groupby/aggregate column(s) {missing} not found in block "
            f"(available: {sorted(acc.columns())})")
    keys = cols[key]
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    # group boundaries
    if len(sorted_keys) == 0:
        return {}
    change = np.nonzero(sorted_keys[1:] != sorted_keys[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(sorted_keys)]])
    out: Dict[str, List[Any]] = {key: []}
    for _, _, out_name in aggs:
        out[out_name] = []
    for s, e in zip(starts, ends):
        idx = order[s:e]
        out[key].append(sorted_keys[s])
        for agg_name, on_col, out_name in aggs:
            col = cols[on_col] if on_col else keys
            out[out_name].append(_KERNELS[agg_name](col[idx]))
    return {k: np.asarray(v) for k, v in out.items()}
