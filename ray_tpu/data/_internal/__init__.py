"""Data internals (reference: ``python/ray/data/_internal/``)."""
