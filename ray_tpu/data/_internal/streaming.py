"""Operator-pipelined streaming executor.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py``
(SURVEY.md §2.5): a running topology of operators with per-operator input
queues and bounded budgets; blocks flow operator-to-operator as they are
produced, so a fast ingest stage and a slow CPU-heavy stage are busy
CONCURRENTLY instead of executing as sequential waves (VERDICT r2
missing #2).

Design (TPU-first economy: the driver is the control loop, workers do the
work — no dedicated supervisor actors):

- The logical plan keeps the map-chain FUSION optimizer (a read→map→map
  chain is still one task per block); only genuinely distinct operators
  (different compute shape, or separated by an all-to-all) become
  pipeline stages.
- One background scheduler thread drives the whole topology: it submits
  tasks for any operator whose input queue is non-empty and whose budget
  allows, harvests completions with ``ray_tpu.wait``, and moves outputs
  to the downstream queue IN SUBMISSION ORDER (deterministic output
  order, out-of-order completion internally).
- Backpressure: each operator may have at most ``DataContext.
  max_tasks_in_flight`` blocks in (inflight + downstream-queue); the
  sink's output queue is bounded the same way, so a slow consumer stalls
  the topology source-first instead of buffering the dataset.
- All-to-all stages are barrier operators: they collect their whole
  input, then run the existing 2-phase shuffle and stream its outputs.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.context import DataContext


class _Op:
    """One pipeline stage: submits one task per input item."""

    def __init__(self, name: str, submit: Callable[[Any], Any],
                 budget: int):
        self.name = name
        self.submit = submit           # input item -> output ref
        self.budget = budget
        self.inq: deque = deque()      # ready input items
        self.inflight: dict = {}       # ref -> seq
        self.results: dict = {}        # seq -> output ref
        self.next_seq = 0              # next submission sequence number
        self.emit_seq = 0              # next sequence to emit downstream
        self.upstream_done = False
        self.emitted = 0

    def done(self) -> bool:
        return (self.upstream_done and not self.inq and not self.inflight
                and not self.results)


class _BarrierOp(_Op):
    """All-to-all: collects ALL inputs, then materializes its outputs via
    the wave shuffle (inherently a barrier in any executor)."""

    def __init__(self, name: str, run: Callable[[List[Any]], List[Any]],
                 budget: int):
        super().__init__(name, submit=None, budget=budget)
        self.run = run
        self.collected: List[Any] = []
        self.ran = False


class StreamingTopology:
    def __init__(self, ops: List[_Op]):
        self.ops = ops
        self.out: deque = deque()      # sink output refs, ordered
        self.error: Optional[BaseException] = None
        self._stop = threading.Event()
        # set ONLY by the scheduler thread, whose view is consistent —
        # the consumer must never compute done-ness itself (it could
        # observe the instant between inq.popleft() and inflight
        # registration and conclude the topology is empty)
        self._finished = threading.Event()
        self._wake = threading.Event()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="data-streaming-exec")
        self._thread.start()

    # ------------------------------------------------------------- driving
    def _downstream(self, i: int) -> Optional[_Op]:
        return self.ops[i + 1] if i + 1 < len(self.ops) else None

    def _room_downstream(self, i: int) -> bool:
        nxt = self._downstream(i)
        if nxt is not None:
            return len(nxt.inq) < nxt.budget
        return len(self.out) < self.ops[i].budget

    def _loop(self) -> None:
        import time as _time
        try:
            while not self._stop.is_set():
                progress = False
                # sink-first: draining downstream frees upstream budget
                for i in range(len(self.ops) - 1, -1, -1):
                    op = self.ops[i]
                    # barrier op: run once its whole input has arrived
                    if isinstance(op, _BarrierOp):
                        op.collected.extend(op.inq)
                        op.inq.clear()
                        if op.upstream_done and not op.ran:
                            # resolve to concrete refs first: the shuffle
                            # fans every input into every reducer
                            outs = op.run(op.collected)
                            op.ran = True
                            for r in outs:
                                op.results[op.next_seq] = r
                                op.next_seq += 1
                            progress = True
                    else:
                        # submit while input + budget allow; completed-but-
                        # unemitted results count against the budget too,
                        # or a stalled consumer lets the op materialize
                        # its whole input into `results`
                        while op.inq and \
                                len(op.inflight) + len(op.results) \
                                < op.budget:
                            item = op.inq.popleft()
                            ref = op.submit(item)
                            op.inflight[ref] = op.next_seq
                            op.next_seq += 1
                            progress = True
                    # emit completed outputs downstream, in order
                    while op.emit_seq in op.results and \
                            self._room_downstream(i):
                        ref = op.results.pop(op.emit_seq)
                        op.emit_seq += 1
                        op.emitted += 1
                        nxt = self._downstream(i)
                        if nxt is not None:
                            nxt.inq.append(ref)
                        else:
                            with self._lock:
                                self.out.append(ref)
                            self._wake.set()
                        progress = True
                    # propagate completion
                    nxt = self._downstream(i)
                    if nxt is not None and not nxt.upstream_done and \
                            op.done():
                        nxt.upstream_done = True
                        progress = True
                if self._all_done():
                    self._finished.set()
                    self._wake.set()
                    return
                if progress:
                    continue
                # harvest: wait on every in-flight ref across ops
                inflight = [r for op in self.ops for r in op.inflight]
                if not inflight:
                    # nothing running and no progress: topology is stalled
                    # on the consumer (sink queue full) — wait for a pull
                    _time.sleep(0.002)
                    continue
                done, _ = ray_tpu.wait(inflight, num_returns=1,
                                       timeout=0.2)
                for ref in done:
                    for op in self.ops:
                        seq = op.inflight.pop(ref, None)
                        if seq is not None:
                            op.results[seq] = ref
                            break
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            self.error = e
            self._finished.set()
            self._wake.set()

    def _all_done(self) -> bool:
        return all(op.done() for op in self.ops)

    # ------------------------------------------------------------ consuming
    def __iter__(self) -> Iterator[Any]:
        while True:
            with self._lock:
                ref = self.out.popleft() if self.out else None
            if ref is not None:
                yield ref
                continue
            if self.error is not None:
                raise self.error
            if self._finished.is_set():
                with self._lock:
                    if not self.out:
                        return
                continue
            self._wake.wait(0.05)
            self._wake.clear()

    def stop(self) -> None:
        self._stop.set()


def build_topology(stages, input_refs=None) -> StreamingTopology:
    """Compile fused logical stages into a running streaming topology."""
    import cloudpickle

    from ray_tpu.data._internal import execution as ex

    ctx = DataContext.get_current()
    budget = max(2, ctx.max_tasks_in_flight)
    ops: List[_Op] = []
    stages = ex._fuse(list(stages))

    def seed(op: _Op) -> _Op:
        """First op consumes the explicit input refs (if any)."""
        if not ops:
            op.inq.extend(input_refs or [])
            op.upstream_done = True
        return op

    i = 0
    while i < len(stages):
        st = stages[i]
        if isinstance(st, ex.ReadStage):
            fns: List[Callable] = []
            i += 1
            while i < len(stages) and isinstance(stages[i], ex.MapStage) \
                    and stages[i].fusable:
                fns.extend(ex._stage_fns(stages[i]))
                i += 1
            fns_blob = cloudpickle.dumps(fns)

            def submit(item, _fb=fns_blob):
                return ex._source_task.remote(item, _fb)
            op = _Op(st.name + ("+Map" if fns else ""), submit, budget)
            op.inq.extend(cloudpickle.dumps(f) for f in st.factories)
            op.upstream_done = True
            ops.append(op)
        elif isinstance(st, ex.MapStage):
            if st.fusable:
                fns = []
                name = "Map"
                while i < len(stages) and \
                        isinstance(stages[i], ex.MapStage) and \
                        stages[i].fusable:
                    fns.extend(ex._stage_fns(stages[i]))
                    name = stages[i].name
                    i += 1
                remote_args = None
            else:
                fns = ex._stage_fns(st)
                name = st.name
                remote_args = st.remote_args
                i += 1
            fns_blob = cloudpickle.dumps(fns)
            task = ex._map_task.options(**remote_args) if remote_args \
                else ex._map_task

            def submit(item, _t=task, _fb=fns_blob):
                return _t.remote(_fb, item)
            ops.append(seed(_Op(name, submit, budget)))
        elif isinstance(st, ex.AllToAllStage):
            i += 1

            def run(collected, _st=st):
                return ex._run_shuffle(_st, list(collected))
            ops.append(seed(_BarrierOp(st.name, run, budget)))
        else:
            raise TypeError(f"unknown stage {st!r}")
    if not ops:
        # empty plan over explicit refs: passthrough barrier
        bop = _BarrierOp("Identity", lambda c: list(c), budget)
        bop.inq.extend(input_refs or [])
        bop.upstream_done = True
        return StreamingTopology([bop])
    return StreamingTopology(ops)
