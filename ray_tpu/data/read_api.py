"""Dataset constructors.

Reference: ``python/ray/data/read_api.py`` — ``range``/``from_items``/
``read_parquet``/``read_csv``/``read_json``/``read_text``/
``read_binary_files``/``read_numpy``/``from_pandas``/``from_numpy``/
``from_arrow``.  Reads are lazy: each file/partition becomes a read-task
factory fused with downstream maps (execution.py).
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, List, Optional, Sequence

import numpy as np

from ray_tpu.data._internal.execution import ReadStage
from ray_tpu.data.block import VALUE_COL, Block, BlockAccessor, block_from_rows
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset


def _n_blocks(total: int, override: Optional[int]) -> int:
    n = override or DataContext.get_current().default_parallelism
    return max(1, min(n, total)) if total else 1


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    nb = _n_blocks(n, override_num_blocks)
    bounds = np.linspace(0, n, nb + 1).astype(int)
    fmt = DataContext.get_current().block_format

    def mk(lo: int, hi: int):
        return lambda: BlockAccessor.batch_to_block(
            {"id": np.arange(lo, hi, dtype=np.int64)}, fmt)
    return Dataset([ReadStage([mk(bounds[i], bounds[i + 1])
                               for i in builtins.range(nb)], "ReadRange")])


def from_items(items: Sequence[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    items = list(items)
    nb = _n_blocks(len(items), override_num_blocks)
    bounds = np.linspace(0, len(items), nb + 1).astype(int)
    fmt = DataContext.get_current().block_format

    def mk(chunk: List[Any]):
        return lambda: block_from_rows(chunk, fmt)
    return Dataset([ReadStage(
        [mk(items[bounds[i]:bounds[i + 1]]) for i in builtins.range(nb)],
        "FromItems")])


def from_numpy(arr: np.ndarray, *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    nb = _n_blocks(len(arr), override_num_blocks)
    chunks = np.array_split(arr, nb)

    def mk(c: np.ndarray):
        return lambda: {VALUE_COL: c}
    return Dataset([ReadStage([mk(c) for c in chunks], "FromNumpy")])


def from_pandas(df: Any, *,
                override_num_blocks: Optional[int] = None) -> Dataset:
    block = BlockAccessor.batch_to_block(df)
    return _from_block(block, override_num_blocks, "FromPandas")


def from_arrow(table: Any, *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    block = BlockAccessor.batch_to_block(table)
    return _from_block(block, override_num_blocks, "FromArrow")


def _from_block(block: Block, override: Optional[int], name: str) -> Dataset:
    acc = BlockAccessor(block)
    nb = _n_blocks(acc.num_rows(), override)
    bounds = np.linspace(0, acc.num_rows(), nb + 1).astype(int)

    def mk(lo: int, hi: int):
        return lambda: acc.slice(lo, hi)
    return Dataset([ReadStage([mk(bounds[i], bounds[i + 1])
                               for i in builtins.range(nb)], name)])


# ------------------------------------------------------------------- files
def _expand_paths(paths: Any, suffix: str = "") -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, f"*{suffix}"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def read_parquet(paths: Any, *, columns: Optional[List[str]] = None,
                 **_compat) -> Dataset:
    files = _expand_paths(paths, ".parquet")
    fmt = DataContext.get_current().block_format

    def mk(f: str):
        def read() -> Block:
            import pyarrow.parquet as pq
            # block_format="arrow": the parquet table IS the block — no
            # numpy conversion anywhere on the read path (VERDICT r3
            # missing #4)
            return BlockAccessor.batch_to_block(
                pq.read_table(f, columns=columns), fmt)
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadParquet")])


def read_csv(paths: Any, **_compat) -> Dataset:
    files = _expand_paths(paths, ".csv")
    fmt = DataContext.get_current().block_format

    def mk(f: str):
        def read() -> Block:
            import pandas as pd
            return BlockAccessor.batch_to_block(pd.read_csv(f), fmt)
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadCSV")])


def read_json(paths: Any, **_compat) -> Dataset:
    files = _expand_paths(paths, ".json")
    fmt = DataContext.get_current().block_format

    def mk(f: str):
        def read() -> Block:
            import pandas as pd
            return BlockAccessor.batch_to_block(
                pd.read_json(f, orient="records", lines=True), fmt)
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadJSON")])


def read_text(paths: Any, **_compat) -> Dataset:
    files = _expand_paths(paths)
    fmt = DataContext.get_current().block_format

    def mk(f: str):
        def read() -> Block:
            with open(f, "r") as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return block_from_rows([{"text": ln} for ln in lines], fmt)
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadText")])


def read_binary_files(paths: Any, **_compat) -> Dataset:
    files = _expand_paths(paths)
    fmt = DataContext.get_current().block_format

    def mk(f: str):
        def read() -> Block:
            with open(f, "rb") as fh:
                data = fh.read()
            return block_from_rows([{"path": f, "bytes": data}], fmt)
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadBinary")])


def read_numpy(paths: Any, **_compat) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def mk(f: str):
        def read() -> Block:
            return {VALUE_COL: np.load(f)}
        return read
    return Dataset([ReadStage([mk(f) for f in files], "ReadNumpy")])
