"""Blocks: the unit of distributed data.

Reference: ``python/ray/data/block.py`` — there a block is a pyarrow Table
in the object store.  TPU-native choice: the DEFAULT block is a dict of
column-major numpy arrays — zero-copy out of the shm object store and
directly ``jax.device_put``-able (SURVEY.md §2.4 "GPU↔object store
interop": the ingest path stages host arrays into HBM).

r4 (VERDICT r3 missing #4): blocks may ALSO be pyarrow Tables —
``DataContext.block_format = "arrow"`` makes every producer (row
builders, batch converters, parquet reads) emit Arrow, with zero-copy
``Table.slice`` / ``concat_tables`` and a schema'd tabular path, exactly
the reference's block representation.  ``BlockAccessor`` dispatches on
the block's type, so the two formats coexist in one dataset pipeline
(e.g. a parquet read in Arrow feeding a numpy-batch map).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], "pyarrow.Table"]  # noqa: F821
VALUE_COL = "item"  # column name for non-tabular datasets (reference: same)


def _is_arrow(block: Any) -> bool:
    return type(block).__module__.split(".")[0] == "pyarrow"


def _block_format() -> str:
    from ray_tpu.data.context import DataContext
    return DataContext.get_current().block_format


def _as_array(values: List[Any]) -> np.ndarray:
    """Column from python values; object dtype for ragged/arbitrary rows."""
    try:
        return np.asarray(values)
    except Exception:  # noqa: BLE001 - truly heterogeneous
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


def _col_to_numpy(col) -> np.ndarray:
    """Arrow column → numpy; tensor columns (FixedSizeList nests, see
    ``_np_to_arrow``) come back as contiguous (N, ...) ndarrays; object
    array for types numpy can't hold."""
    import pyarrow as pa
    col = col.combine_chunks() if hasattr(col, "combine_chunks") else col
    shape = []
    while pa.types.is_fixed_size_list(col.type):
        shape.append(col.type.list_size)
        col = col.flatten()          # offset-aware: works on sliced views
    try:
        vals = col.to_numpy(zero_copy_only=False)
    except Exception:  # noqa: BLE001 - nested / union types
        vals = _as_array(col.to_pylist())
    if shape:
        return vals.reshape((-1, *shape))
    return vals


def _np_to_arrow(values: Any):
    """numpy (or listlike) → Arrow array; ndim>1 tensors become nested
    FixedSizeList columns (the Arrow tensor representation — numpy-block
    pipelines carrying image/embedding columns keep working when
    ``block_format="arrow"``)."""
    import pyarrow as pa
    a = values if isinstance(values, np.ndarray) else _as_array(list(values))
    if a.dtype == object:
        return pa.array(a.tolist())
    if a.ndim <= 1:
        return pa.array(a)
    out = pa.FixedSizeListArray.from_arrays(pa.array(a.reshape(-1)),
                                            a.shape[-1])
    for dim in reversed(a.shape[1:-1]):
        out = pa.FixedSizeListArray.from_arrays(out, dim)
    return out


def block_from_rows(rows: Sequence[Any],
                    block_format: Optional[str] = None) -> Block:
    """Rows (dicts or scalars) → block in the context's format."""
    fmt = block_format or _block_format()
    if not rows:
        return {} if fmt != "arrow" else _empty_arrow()
    # columnize through numpy for BOTH formats: ndarray-valued row fields
    # (embeddings/images) stack into (N, ...) tensor columns, which the
    # arrow conversion then stores as FixedSizeList — from_pylist would
    # produce ragged list<T> columns that round-trip as object arrays
    if isinstance(rows[0], dict):
        cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
        for r in rows:
            for k in cols:
                cols[k].append(r[k])
        block = {k: _as_array(v) for k, v in cols.items()}
    else:
        block = {VALUE_COL: _as_array(list(rows))}
    if fmt == "arrow":
        return BlockAccessor.batch_to_block(block, "arrow")
    return block


def _empty_arrow():
    import pyarrow as pa
    return pa.table({})


class BlockAccessor:
    """Uniform view over a block (reference: ``BlockAccessor``).

    ``BlockAccessor(block)`` (or ``for_block``) returns the numpy or the
    Arrow accessor depending on the block's type — call sites never
    branch on format.
    """

    def __new__(cls, block: Block = None):
        # block defaults to None so pickle's ``cls.__new__(cls)`` (an
        # accessor captured in a task closure) can reconstruct instances
        if cls is BlockAccessor and _is_arrow(block):
            return super().__new__(ArrowBlockAccessor)
        return super().__new__(cls)

    def __reduce__(self):
        # dispatching __new__ + default __reduce_ex__ lose the subclass
        # on round-trip; rebuild from the block itself
        return (BlockAccessor, (self._b,))

    def __init__(self, block: Block):
        self._b = block if block is not None else {}

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if not self._b:
            return 0
        return len(next(iter(self._b.values())))

    def size_bytes(self) -> int:
        return sum(a.nbytes if hasattr(a, "nbytes") else 64 * len(a)
                   for a in self._b.values())

    def columns(self) -> List[str]:
        return list(self._b.keys())

    def schema(self) -> Dict[str, Any]:
        return {k: v.dtype for k, v in self._b.items()}

    # ------------------------------------------------------------- columns
    def get_column(self, name: str) -> Optional[np.ndarray]:
        return self._b.get(name)

    def select(self, cols: List[str]) -> Block:
        return {k: self._b[k] for k in cols}

    def drop(self, cols: List[str]) -> Block:
        return {k: v for k, v in self._b.items() if k not in cols}

    def rename(self, mapping: Dict[str, str]) -> Block:
        return {mapping.get(k, k): v for k, v in self._b.items()}

    def with_column(self, name: str, values: Any) -> Block:
        out = dict(self._b)
        out[name] = np.asarray(values)
        return out

    def merge(self, other: Block, suffix: str = "_1") -> Block:
        """Column-concat two equal-row blocks (zip); clashes get suffix."""
        out = dict(self._b)
        for k, v in BlockAccessor(other).to_batch("numpy").items():
            out[k if k not in self._b else f"{k}{suffix}"] = v
        return out

    # ------------------------------------------------------------- slicing
    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._b.items()}

    def take_idx(self, idx: np.ndarray) -> Block:
        return {k: v[idx] for k, v in self._b.items()}

    # ----------------------------------------------------------- iteration
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        keys = list(self._b.keys())
        for i in range(self.num_rows()):
            yield {k: self._b[k][i] for k in keys}

    # --------------------------------------------------------- conversions
    def to_batch(self, batch_format: str = "numpy") -> Any:
        if batch_format in ("numpy", "default", None):
            return dict(self._b)
        if batch_format == "pandas":
            import pandas as pd
            return pd.DataFrame({k: list(v) if v.dtype == object else v
                                 for k, v in self._b.items()})
        if batch_format == "pyarrow":
            import pyarrow as pa
            # _np_to_arrow: tensor (ndim>1) columns become FixedSizeList
            # instead of crashing pa.table (mixed-format concat/zip path)
            return pa.table({k: _np_to_arrow(v)
                             for k, v in self._b.items()})
        raise ValueError(f"unknown batch_format {batch_format!r}")

    @staticmethod
    def batch_to_block(batch: Any,
                       block_format: Optional[str] = None) -> Block:
        """Convert a user-facing batch to a block in the context format."""
        fmt = block_format or _block_format()
        if batch is None:
            return _empty_arrow() if fmt == "arrow" else {}
        mod = type(batch).__module__.split(".")[0]
        if fmt == "arrow":
            import pyarrow as pa
            if mod == "pyarrow":
                return batch          # zero conversion: the table IS a block
            if isinstance(batch, dict):
                return pa.table({k: _np_to_arrow(v)
                                 for k, v in batch.items()})
            if mod == "pandas":
                return pa.Table.from_pandas(batch, preserve_index=False)
            if isinstance(batch, np.ndarray):
                return pa.table({VALUE_COL: _np_to_arrow(batch)})
            raise TypeError(f"cannot convert batch of type {type(batch)}")
        if isinstance(batch, dict):
            return {k: v if isinstance(v, np.ndarray) else _as_array(list(v))
                    for k, v in batch.items()}
        if mod == "pandas":
            return {k: _as_array(batch[k].tolist())
                    if batch[k].dtype == object else batch[k].to_numpy()
                    for k in batch.columns}
        if mod == "pyarrow":
            return {name: _col_to_numpy(batch.column(name))
                    for name in batch.column_names}
        if isinstance(batch, np.ndarray):
            return {VALUE_COL: batch}
        raise TypeError(f"cannot convert batch of type {type(batch)}")


class ArrowBlockAccessor(BlockAccessor):
    """Accessor over a ``pyarrow.Table`` block.

    Slices are zero-copy views (Arrow buffer offsets); concat is
    zero-copy chunk stitching — neither touches the column bytes, which
    is the entire point of the Arrow path (reference:
    ``ArrowBlockAccessor`` in ``python/ray/data/_internal/arrow_block.py``
    — contract only, implementation independent).
    """

    def num_rows(self) -> int:
        return self._b.num_rows

    def size_bytes(self) -> int:
        return self._b.nbytes

    def columns(self) -> List[str]:
        return list(self._b.column_names)

    def schema(self) -> Dict[str, Any]:
        return {f.name: f.type for f in self._b.schema}

    # ------------------------------------------------------------- columns
    def get_column(self, name: str) -> Optional[np.ndarray]:
        if name not in self._b.column_names:
            return None
        return _col_to_numpy(self._b.column(name))

    def select(self, cols: List[str]) -> Block:
        return self._b.select(cols)

    def drop(self, cols: List[str]) -> Block:
        keep = [c for c in self._b.column_names if c not in cols]
        return self._b.select(keep)

    def rename(self, mapping: Dict[str, str]) -> Block:
        return self._b.rename_columns(
            [mapping.get(c, c) for c in self._b.column_names])

    def with_column(self, name: str, values: Any) -> Block:
        arr = _np_to_arrow(values)
        if name in self._b.column_names:
            i = self._b.column_names.index(name)
            return self._b.set_column(i, name, arr)
        return self._b.append_column(name, arr)

    def merge(self, other: Block, suffix: str = "_1") -> Block:
        out = self._b
        have = set(self._b.column_names)
        ob = other if _is_arrow(other) else BlockAccessor(
            other).to_batch("pyarrow")
        for name in ob.column_names:
            out = out.append_column(
                name if name not in have else f"{name}{suffix}",
                ob.column(name))
        return out

    # ------------------------------------------------------------- slicing
    def slice(self, start: int, end: int) -> Block:
        return self._b.slice(start, max(0, end - start))

    def take_idx(self, idx: np.ndarray) -> Block:
        return self._b.take(np.asarray(idx))

    # ----------------------------------------------------------- iteration
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self._b.to_batches():
            yield from batch.to_pylist()

    # --------------------------------------------------------- conversions
    def to_batch(self, batch_format: str = "numpy") -> Any:
        if batch_format in ("numpy", "default", None):
            return {name: _col_to_numpy(self._b.column(name))
                    for name in self._b.column_names}
        if batch_format == "pandas":
            return self._b.to_pandas()
        if batch_format == "pyarrow":
            return self._b                      # zero copy
        raise ValueError(f"unknown batch_format {batch_format!r}")


def concat_blocks(blocks: Sequence[Block],
                  block_format: Optional[str] = None) -> Block:
    """``block_format`` matters only for the all-empty case: worker-side
    callers must pass their driver-captured format (the worker's
    DataContext singleton is a fresh default), or the inputs' own format
    decides."""
    nonempty = [b for b in blocks
                if b is not None and BlockAccessor(b).num_rows()]
    if not nonempty:
        fmt = block_format
        if fmt is None and any(_is_arrow(b) for b in blocks
                               if b is not None):
            fmt = "arrow"
        return _empty_arrow() if (fmt or _block_format()) == "arrow" else {}
    blocks = nonempty
    if any(_is_arrow(b) for b in blocks):
        import pyarrow as pa
        tables = [b if _is_arrow(b)
                  else BlockAccessor(b).to_batch("pyarrow") for b in blocks]
        return pa.concat_tables(tables, promote_options="default")
    keys = list(blocks[0].keys())
    out = {}
    for k in keys:
        arrs = [b[k] for b in blocks]
        if any(a.dtype == object for a in arrs):
            merged = np.empty(sum(len(a) for a in arrs), dtype=object)
            i = 0
            for a in arrs:
                merged[i:i + len(a)] = a
                i += len(a)
            out[k] = merged
        else:
            out[k] = np.concatenate(arrs)
    return out
