"""Blocks: the unit of distributed data.

Reference: ``python/ray/data/block.py`` — there a block is a pyarrow Table
in the object store.  TPU-native choice: the canonical block is a dict of
column-major numpy arrays — zero-copy out of the shm object store and
directly ``jax.device_put``-able (SURVEY.md §2.4 "GPU↔object store
interop": the ingest path stages host arrays into HBM).  Arrow/pandas
appear only at IO boundaries and in ``batch_format`` conversions.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Sequence

import numpy as np

Block = Dict[str, np.ndarray]
VALUE_COL = "item"  # column name for non-tabular datasets (reference: same)


def _as_array(values: List[Any]) -> np.ndarray:
    """Column from python values; object dtype for ragged/arbitrary rows."""
    try:
        return np.asarray(values)
    except Exception:  # noqa: BLE001 - truly heterogeneous
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


def block_from_rows(rows: Sequence[Any]) -> Block:
    """Rows (dicts or scalars) → column block."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
        for r in rows:
            for k in cols:
                cols[k].append(r[k])
        return {k: _as_array(v) for k, v in cols.items()}
    return {VALUE_COL: _as_array(list(rows))}


class BlockAccessor:
    """Uniform view over a block (reference: ``BlockAccessor``)."""

    def __init__(self, block: Block):
        self._b = block or {}

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if not self._b:
            return 0
        return len(next(iter(self._b.values())))

    def size_bytes(self) -> int:
        return sum(a.nbytes if hasattr(a, "nbytes") else 64 * len(a)
                   for a in self._b.values())

    def columns(self) -> List[str]:
        return list(self._b.keys())

    def schema(self) -> Dict[str, Any]:
        return {k: v.dtype for k, v in self._b.items()}

    # ------------------------------------------------------------- slicing
    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._b.items()}

    def take_idx(self, idx: np.ndarray) -> Block:
        return {k: v[idx] for k, v in self._b.items()}

    # ----------------------------------------------------------- iteration
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        keys = list(self._b.keys())
        for i in range(self.num_rows()):
            yield {k: self._b[k][i] for k in keys}

    # --------------------------------------------------------- conversions
    def to_batch(self, batch_format: str = "numpy") -> Any:
        if batch_format in ("numpy", "default", None):
            return dict(self._b)
        if batch_format == "pandas":
            import pandas as pd
            return pd.DataFrame({k: list(v) if v.dtype == object else v
                                 for k, v in self._b.items()})
        if batch_format == "pyarrow":
            import pyarrow as pa
            return pa.table({k: list(v) if v.dtype == object else v
                             for k, v in self._b.items()})
        raise ValueError(f"unknown batch_format {batch_format!r}")

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        if batch is None:
            return {}
        if isinstance(batch, dict):
            return {k: v if isinstance(v, np.ndarray) else _as_array(list(v))
                    for k, v in batch.items()}
        mod = type(batch).__module__
        if mod.startswith("pandas"):
            return {k: _as_array(batch[k].tolist())
                    if batch[k].dtype == object else batch[k].to_numpy()
                    for k in batch.columns}
        if mod.startswith("pyarrow"):
            return {name: _as_array(batch.column(name).to_pylist())
                    for name in batch.column_names}
        if isinstance(batch, np.ndarray):
            return {VALUE_COL: batch}
        raise TypeError(f"cannot convert batch of type {type(batch)}")


def concat_blocks(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if b and BlockAccessor(b).num_rows()]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    out = {}
    for k in keys:
        arrs = [b[k] for b in blocks]
        if any(a.dtype == object for a in arrs):
            merged = np.empty(sum(len(a) for a in arrs), dtype=object)
            i = 0
            for a in arrs:
                merged[i:i + len(a)] = a
                i += len(a)
            out[k] = merged
        else:
            out[k] = np.concatenate(arrs)
    return out
