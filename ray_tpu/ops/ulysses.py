"""Ulysses-style sequence parallelism: all-to-all over heads.

Greenfield TPU component (SURVEY.md §5.7).  Alternative to ring attention
when ``n_heads >= context_parallel_size``: instead of rotating KV blocks,
one all-to-all re-shards (B, T/n, H, D) → (B, T, H/n, D) so every device
holds FULL sequences for a subset of heads, runs plain (fused) attention
locally, and a second all-to-all restores sequence sharding.

Cost: 2 all-to-alls of the activations vs ring's (n-1) KV rotations —
cheaper on ICI for moderate sequence lengths; ring wins when T is huge
(all-to-all volume scales with T) or when H < ring size.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import dense_attention


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str, causal: bool = True) -> jax.Array:
    """Per-shard Ulysses attention; call inside shard_map.

    q/k/v: (B, T_local, H, D) sequence-sharded; H must be divisible by the
    axis size.  Returns (B, T_local, H, D).
    """
    # (B, T/n, H, D) -> (B, T, H/n, D): split heads across the axis, gather
    # the sequence.  tiled=True concatenates rather than stacking.
    a2a = partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    qg = a2a(q, split_axis=2, concat_axis=1)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)
    # Full sequence present locally: positions are global, plain causal mask.
    out = dense_attention(qg, kg, vg, causal=causal)
    return a2a(out, split_axis=1, concat_axis=2)


def ulysses_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                              mesh, axis_name: str = "context",
                              batch_axes=("data", "fsdp"),
                              causal: bool = True) -> jax.Array:
    """GSPMD-land wrapper: global (B,T,H,D) → shard_map Ulysses.

    Heads stay UNSHARDED over ``tensor`` here: Ulysses consumes the head
    dimension for sequence parallelism (head_parallel = context axis).
    """
    n = mesh.shape[axis_name]
    if n == 1:
        return dense_attention(q, k, v, causal=causal)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs n_heads ({q.shape[2]}) divisible by "
            f"{axis_name} axis size ({n})")
    spec = P(tuple(a for a in batch_axes if a in mesh.shape), axis_name,
             None, None)
    inner = partial(ulysses_attention, axis_name=axis_name, causal=causal)
    from ray_tpu._private.jax_compat import shard_map
    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention_for_model(q, k, v, cfg=None, *,
                                axis_name: Optional[str] = "context"):
    """Model hook (``GPT2Config.attn_impl='ulysses'``)."""
    from ray_tpu.parallel import mesh as mesh_lib
    axis_name = axis_name or "context"
    mesh = mesh_lib.get_ambient_mesh()
    if mesh is None or axis_name not in mesh.shape \
            or mesh.shape[axis_name] == 1:
        return dense_attention(q, k, v, causal=True)
    return ulysses_attention_sharded(q, k, v, mesh=mesh,
                                     axis_name=axis_name, causal=True)
