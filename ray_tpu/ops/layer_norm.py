"""Pallas fused LayerNorm (TPU) with a fused backward.

Why this exists (r3 device-trace finding, benchmarks/step_decompose.py):
with LayerNorm left to XLA, the compiler chooses a T-minor layout for its
LN fusions (trace: ~32ms/step of LN-backward fusions at the flagship
GPT-2 bench shape, all {1,2,0} layouts).  The Pallas kernel pins the
natural E-minor layout (Pallas operands use default minor-to-major) and
fuses the whole normalize-scale-shift into one VMEM pass each way —
LN-attributed trace time drops to ~4ms/step.  Step-level impact at that
config measured ~neutral (XLA had fused most LN cost into neighboring
ops), so this kernel's value is layout stability + trace legibility +
shapes where XLA's T-minor choice does force stream relayouts.

Semantics match models/gpt2._layer_norm: statistics and affine math in
f32, output cast back to the input dtype.  The backward saves only the
per-row (mu, rstd) f32 stats — O(rows), not O(rows·E) — and emits
per-block partial reductions for dscale/dbias that are summed outside
the kernel (n_blocks × E, trivial).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_ROWS = 512


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mu_ref, rstd_ref, *,
                eps: float):
    x = x_ref[...].astype(jnp.float32)                # (R, E)
    mu = x.mean(axis=-1, keepdims=True)
    xc = x - mu
    var = (xc * xc).mean(axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * scale_ref[...].astype(jnp.float32) \
        + bias_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu[:, 0][None, :]                   # (1, R) lanes
    rstd_ref[...] = rstd[:, 0][None, :]


def _bwd_kernel(x_ref, scale_ref, g_ref, mu_ref, rstd_ref,
                dx_ref, dscale_ref, dbias_ref):
    x = x_ref[...].astype(jnp.float32)                # (R, E)
    g = g_ref[...].astype(jnp.float32)
    mu = jnp.transpose(mu_ref[...])                   # (R, 1)
    rstd = jnp.transpose(rstd_ref[...])
    xhat = (x - mu) * rstd
    gs = g * scale_ref[...].astype(jnp.float32)
    m1 = gs.mean(axis=-1, keepdims=True)
    m2 = (gs * xhat).mean(axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gs - m1 - xhat * m2)).astype(dx_ref.dtype)
    dscale_ref[...] = jnp.sum(g * xhat, axis=0)[None, None, :]  # partial
    dbias_ref[...] = jnp.sum(g, axis=0)[None, None, :]


def _resolve(N: int, interpret: Optional[bool]) -> Tuple[int, bool]:
    rows = DEFAULT_ROWS
    while rows > 8 and N % rows:
        rows //= 2
    if N % rows:
        rows = N  # single block
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return rows, interpret


def _ln_fwd(x2, scale, bias, eps, interpret):
    N, E = x2.shape
    rows, interpret = _resolve(N, interpret)
    nb = N // rows
    y, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rows, E), lambda i: (i, 0)),
            pl.BlockSpec((E,), lambda i: (0,)),
            pl.BlockSpec((E,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((rows, E), lambda i: (i, 0)),
            pl.BlockSpec((1, rows), lambda i: (0, i)),
            pl.BlockSpec((1, rows), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, E), x2.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale, bias)
    return y, mu, rstd


def _ln_bwd(x2, scale, g2, mu, rstd, interpret):
    N, E = x2.shape
    rows, interpret = _resolve(N, interpret)
    nb = N // rows
    dx, dscale_p, dbias_p = pl.pallas_call(
        _bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rows, E), lambda i: (i, 0)),
            pl.BlockSpec((E,), lambda i: (0,)),
            pl.BlockSpec((rows, E), lambda i: (i, 0)),
            pl.BlockSpec((1, rows), lambda i: (0, i)),
            pl.BlockSpec((1, rows), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((rows, E), lambda i: (i, 0)),
            pl.BlockSpec((1, 1, E), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, E), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, E), x2.dtype),
            jax.ShapeDtypeStruct((nb, 1, E), jnp.float32),
            jax.ShapeDtypeStruct((nb, 1, E), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale, g2, mu, rstd)
    return dx, dscale_p.sum(axis=(0, 1)), dbias_p.sum(axis=(0, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-5,
               interpret: Optional[bool] = None) -> jax.Array:
    """LayerNorm over the last axis; f32 statistics, affine in f32,
    output in x.dtype.  x: (..., E); scale/bias: (E,)."""
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    y, _, _ = _ln_fwd(x2, scale, bias, eps, interpret)
    return y.reshape(shape)


def _vjp_fwd(x, scale, bias, eps, interpret):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    y, mu, rstd = _ln_fwd(x2, scale, bias, eps, interpret)
    return y.reshape(shape), (x2, scale, mu, rstd, shape)


def _vjp_bwd(eps, interpret, res, g):
    x2, scale, mu, rstd, shape = res
    g2 = g.reshape(-1, shape[-1])
    dx, dscale, dbias = _ln_bwd(x2, scale, g2, mu, rstd, interpret)
    return (dx.reshape(shape), dscale.astype(scale.dtype),
            dbias.astype(scale.dtype))


layer_norm.defvjp(_vjp_fwd, _vjp_bwd)
