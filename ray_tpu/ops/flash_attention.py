"""Pallas flash attention (TPU kernel) with a fused one-pass backward.

Greenfield TPU component (SURVEY.md §5.7): tiled online-softmax attention
that never materializes the T×T score matrix in HBM.  Each grid step owns
one (batch·head, q-block) tile in VMEM and streams K/V blocks through the
MXU with running (m, l, acc) accumulators — the classic flash schedule,
expressed the Pallas way (grid + BlockSpecs; see
/opt/skills/guides/pallas_guide.md).

Design notes (r3 device-trace driven — benchmarks/step_decompose.py,
flash_kernel_decompose.py):
- Probabilities use ``exp2`` with the 1/sqrt(D) scale and log2(e) folded
  into the score matmul's epilogue multiply — the VPU transcendental is
  the kernel's throughput bound, so no extra multiplies ride with it.
- Causal masking is specialized: only the diagonal (q-block == k-block)
  tile pays the iota/compare/select chain; strictly-lower tiles skip it.
- The row-statistics residual (logsumexp) is stored COMPACT as (B·H, T)
  f32 — the r2 kernel lane-replicated it to (B·H, T, 128), which cost
  128× the HBM (200MB/layer at the flagship shape) and made saving it
  across a remat boundary pointless.  The (1, block) lane-vector ↔
  (block, 1) sublane-vector relayout this needs is a few hundred elements
  per tile — noise next to the exp chain.
- The backward is ONE kernel, gridded over (batch·head, k-block): k/v
  tiles stay resident while an inner loop walks q-blocks ≥ the diagonal;
  each (q,k) tile computes probabilities ONCE (the r2 two-kernel design
  re-ran the exp chain in both dQ and dK/dV passes) and emits all three
  gradient contributions: dk/dv accumulate in VMEM scratch for the
  resident k-block; dq accumulates into a full-T f32 output block whose
  index map is constant in the k-grid axis, so Mosaic keeps it VMEM-
  resident across k-steps and writes it back once.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ray_tpu.ops.attention import NEG_INF

DEFAULT_BLOCK = 128
LOG2E = math.log2(math.e)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *lse_out, block_q: int,
                  block_k: int, seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    # Keep q/k/v in their storage dtype (bf16) for the MXU — f32 inputs
    # would quarter matmul throughput; accumulation stays f32 via
    # preferred_element_type.  scale*log2(e) folds into the score
    # multiply so the exp2 chain carries no extra VPU work.
    q = q_ref[0]                                      # (block_q, D) bf16
    D = q.shape[-1]
    s_scale = scale * LOG2E

    def tile(j, carry, masked):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * s_scale
        if masked:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF)
    l0 = jnp.zeros((block_q,), jnp.float32)
    nblocks = seq_len // block_k
    if causal:
        # Strictly-lower tiles (j < qi) are fully visible: no mask chain.
        acc, m, l = lax.fori_loop(
            0, qi, lambda j, c: tile(j, c, masked=False), (acc0, m0, l0))
        acc, m, l = tile(qi, (acc, m, l), masked=True)  # diagonal tile
    else:
        acc, m, l = lax.fori_loop(
            0, nblocks, lambda j, c: tile(j, c, masked=False),
            (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    if lse_out:                                       # vjp forward only
        # lse in base-2 units (m + log2 l); consumers stay in base 2.
        lse = m + jnp.log2(l)                         # (block_q,)
        lse_out[0][0, 0] = lse                        # lse rides the lanes


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dq_acc, *,
                block_q: int, block_k: int, seq_len: int, causal: bool,
                scale: float):
    """One-pass backward: grid (B·H, k-block); inner loop over q-blocks.

    Each (q, k) tile: recompute s and p (one exp2 chain), then
      dv += pᵀ·do        dp = do·vᵀ        ds = p*(dp-delta)
      dk += dsᵀ·q        dq[i] += ds·(k·scale)
    dq accumulates in an f32 VMEM scratch across the k grid axis and is
    flushed (bf16) once per (B·H) row at the last k-step.

    r4 notes (VERDICT r3 weak #1; trace data in step_breakdown_r04.md):
    - delta = Σ do·o depends only on the q-block but the r3 kernel
      recomputed it for EVERY (q, k) tile — T/block_k times over.  It is
      a precomputed (B·H, 1, T) input now, and ``o`` leaves the kernel
      entirely (with its 100MB/layer flatten transpose).
    - The 1/sqrt(D) factor on ds cost a full (block_q, block_k) VPU
      multiply per tile; it now rides the O(block·D) operands instead:
      pre-scaled k for the dq dot, post-loop scale on the dk accumulator.
    - The kernel's floor is MXU shape-efficiency, not the exp2 chain:
      all five dots have a 64-wide contracting or output dimension
      (D=64) against the 128-deep systolic array.
    """
    kj = pl.program_id(1)
    nq = seq_len // block_q
    nk = seq_len // block_k
    k = k_ref[0]                                      # (block_k, D)
    v = v_ref[0]
    ks = (k.astype(jnp.float32) * scale).astype(k.dtype)
    D = k.shape[-1]
    s_scale = scale * LOG2E

    @pl.when(kj == 0)
    def _init_dq():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def tile(i, carry, masked):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse_lanes = lse_ref[0, 0, pl.ds(i * block_q, block_q)]  # lanes
        lse_rows = jnp.transpose(lse_lanes[None, :])         # (block_q, 1)
        d_lanes = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = jnp.transpose(d_lanes[None, :])              # (block_q, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * s_scale
        if masked:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp2(s - lse_rows)                    # (block_q, block_k)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                # scale deferred to dk/dq below
        dsl = ds.astype(k.dtype)
        dk = dk + jax.lax.dot_general(
            dsl, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_tile = jax.lax.dot_general(
            dsl, ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        sl = pl.ds(i * block_q, block_q)
        dq_acc[sl, :] = dq_acc[sl, :] + dq_tile
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    if causal:
        # k-block kj is seen by q-blocks i ≥ kj: diagonal first (masked),
        # then the fully-visible strictly-lower rows.
        dk, dv = tile(kj, (dk0, dv0), masked=True)
        dk, dv = lax.fori_loop(
            kj + 1, nq, lambda i, c: tile(i, c, masked=False), (dk, dv))
    else:
        dk, dv = lax.fori_loop(
            0, nq, lambda i, c: tile(i, c, masked=False), (dk0, dv0))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(kj == nk - 1)
    def _flush_dq():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flatten(x):
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unflatten(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _resolve(block_size, T, interpret):
    if block_size is None:
        block_size = pick_block_size(T)
    bs = min(block_size, T)
    if T % bs:
        raise ValueError(f"seq len {T} not divisible by block {bs}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return bs, interpret


def _flash_forward_lse_flat(qf, kf, vf, *, causal: bool, bs: int,
                            interpret: bool, want_lse: bool = True):
    """Core forward on kernel-layout (B·H, T, D) operands.

    ``want_lse=False`` (the primal / inference path) skips computing
    and writing the lse tensor — it is only a residual for the fused
    backward, and Pallas cannot DCE a declared output."""
    BH, T, D = qf.shape
    scale = 1.0 / math.sqrt(D)
    kernel = functools.partial(_flash_kernel, block_q=bs, block_k=bs,
                               seq_len=T, causal=causal, scale=scale)
    out_specs = [pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct((BH, T, D), qf.dtype)]
    if want_lse:
        # Compact (B·H, 1, T) f32 — lse rides the lane axis; the unit
        # middle dim satisfies Mosaic's (8,128) last-two-dims tiling rule.
        out_specs.append(
            pl.BlockSpec((1, 1, bs), lambda bh, qi: (bh, 0, qi)))
        out_shape.append(jax.ShapeDtypeStruct((BH, 1, T), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(BH, T // bs),
        in_specs=[
            pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    return res if want_lse else (res[0], None)


def _flash_forward_lse(q, k, v, *, causal: bool, block_size: int,
                       interpret: Optional[bool], want_lse: bool = True):
    B, T, H, D = q.shape
    bs, interpret = _resolve(block_size, T, interpret)
    # (B,T,H,D) -> (B*H, T, D): one grid row per (batch, head).
    qf, kf, vf = _flatten(q), _flatten(k), _flatten(v)
    out, lse = _flash_forward_lse_flat(qf, kf, vf, causal=causal, bs=bs,
                                       interpret=interpret,
                                       want_lse=want_lse)
    return _unflatten(out, B, H), lse


def _flash_backward_flat(qf, kf, vf, lse, delta, dof, *, causal: bool,
                         block_size: int, interpret: Optional[bool]):
    """Backward on kernel-layout (B·H, T, D) operands.

    ``out`` never enters: its only backward use is delta = Σ do·o, which
    the caller precomputes in the residual layout (the r3 kernel both
    re-flattened out — a 100MB physical copy per GPT-2-small layer at
    b32/s1024 — and recomputed delta per (q,k) tile).  dq accumulates
    across the k-grid axis in an f32 VMEM scratch and is written back
    bf16 once per (B·H) row — half the HBM traffic of the r3 f32 dq
    output.
    """
    BH, T, D = qf.shape
    # NOTE: a 1024-wide backward block measured marginally faster in the
    # standalone kernel bench but 20x SLOWER inside the remat'd train
    # step (VMEM pressure next to the replayed ops) — block choice is
    # shared with the forward on purpose.
    bs, interpret = _resolve(block_size, T, interpret)
    scale = 1.0 / math.sqrt(D)

    from jax.experimental.pallas import tpu as pltpu

    kspec = pl.BlockSpec((1, bs, D), lambda bh, kj: (bh, kj, 0))
    fullspec = pl.BlockSpec((1, T, D), lambda bh, kj: (bh, 0, 0))
    # dq: constant index along the k grid axis → flushed from scratch at
    # the last k-step.
    dqspec = pl.BlockSpec((1, T, D), lambda bh, kj: (bh, 0, 0))
    rowspec = pl.BlockSpec((1, 1, T), lambda bh, kj: (bh, 0, 0))

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, block_q=bs, block_k=bs, seq_len=T,
                          causal=causal, scale=scale),
        grid=(BH, T // bs),
        in_specs=[fullspec, kspec, kspec, fullspec, rowspec, rowspec],
        out_specs=[dqspec, kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((BH, T, D), qf.dtype),
                   jax.ShapeDtypeStruct((BH, T, D), kf.dtype),
                   jax.ShapeDtypeStruct((BH, T, D), vf.dtype)],
        scratch_shapes=[pltpu.VMEM((T, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_size: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """(B,T,H,D)×3 → (B,T,H,D) tiled attention; differentiable.

    ``block_size=None`` (default) resolves via ``pick_block_size`` — the
    measured-fastest tile for the sequence length — so every caller gets
    the tuned configuration without opting in."""
    out, _ = _flash_forward_lse(q, k, v, causal=causal,
                                block_size=block_size, interpret=interpret,
                                want_lse=False)
    return out


def _fwd(q, k, v, causal, block_size, interpret):
    out, lse = _flash_forward_lse(q, k, v, causal=causal,
                                  block_size=block_size, interpret=interpret)
    # Name the backward residuals so a jax.checkpoint policy
    # (save_only_these_names, models/gpt2.py remat_policy="attn") can pin
    # them across the remat boundary: saving out + the compact lse
    # (~50MB + 1.6MB per GPT-2-small layer at b32/s1024) lets the
    # rematerialized backward skip re-running the whole flash forward
    # kernel.  (An r4 experiment that pinned q/k/v in the KERNEL layout
    # instead of the projection output measured +15ms on the forward
    # scan — three transposed stack-writes beat one contiguous one —
    # and was reverted; trace data in step_breakdown_r04.md.)
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_attn_out")
    lse = checkpoint_name(lse, "flash_attn_lse")
    return out, (q, k, v, out, lse)


def _bwd(causal, block_size, interpret, res, g):
    q, k, v, out, lse = res
    B, H = g.shape[0], g.shape[2]       # cotangent is (B, T, H, D)
    # delta = Σ_D do·o computed in the RESIDUAL layout — one fused
    # multiply-reduce pass; ``out`` then never needs flattening (the r3
    # backward paid a 100MB physical transpose of it per layer just to
    # hand the kernel a tensor it only reduced over D).
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                          # (B, T, H) f32
    delta = delta.transpose(0, 2, 1).reshape(B * H, 1, -1)  # tiny: BHT f32
    qf, kf, vf = _flatten(q), _flatten(k), _flatten(v)
    dof = _flatten(g).astype(q.dtype)
    dq, dk, dv = _flash_backward_flat(qf, kf, vf, lse, delta, dof,
                                      causal=causal, block_size=block_size,
                                      interpret=interpret)
    # The bf16 dq emerges from VMEM scratch; converts fuse into the
    # unflatten transposes' single HBM pass.
    return (_unflatten(dq, B, H).astype(q.dtype),
            _unflatten(dk, B, H).astype(k.dtype),
            _unflatten(dv, B, H).astype(v.dtype))


flash_attention.defvjp(_fwd, _bwd)


def pick_block_size(T: int) -> int:
    """Largest block in {512, 256, 128} dividing T.  Measured on v5e
    (benchmarks/attention_bench.py --seqs 1024 --tokens 32768): fwd+bwd
    per-call improves monotonically 128→512 — bigger q/k tiles amortize
    the per-grid-step VPU chain (mask iota, exp, rescale) and feed the
    MXU (block, D)x(D, block) dots with fuller tiles."""
    for bs in (512, 256, 128):
        if T % bs == 0:
            return bs
    return min(T, DEFAULT_BLOCK)


def flash_attention_for_model(q, k, v, cfg=None, **_):
    """Model hook (``attn_impl='flash'``, and what ``'auto'`` resolves
    to on TPU).  Sequence lengths with no clean tile (e.g. a 192-token
    serving bucket: best block 128 does not divide) fall back to the
    XLA dense path instead of raising — the hook serves every model
    entry point (train step, serving prefill), and an odd-shaped
    bucket must not take the engine down.  Direct ``flash_attention``
    callers still get the loud ValueError."""
    T = q.shape[1]
    if T % pick_block_size(T):
        from ray_tpu.ops.attention import dense_attention
        return dense_attention(q, k, v, causal=True)
    return flash_attention(q, k, v, True)
