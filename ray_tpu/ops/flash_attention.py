"""Pallas flash attention (TPU kernel) with a fused Pallas backward.

Greenfield TPU component (SURVEY.md §5.7): tiled online-softmax attention
that never materializes the T×T score matrix in HBM.  Each grid step owns
one (batch·head, q-block) tile in VMEM and streams K/V blocks through the
MXU with running (m, l, acc) accumulators — the classic flash schedule,
expressed the Pallas way (grid + BlockSpecs; see
/opt/skills/guides/pallas_guide.md).

Differentiation: the forward kernel additionally emits the per-row
logsumexp (lane-replicated to a 128-wide minor dim — Mosaic's tiling
needs ≥(8,128) blocks, so row stats ride a broadcast lane axis, same
trick as jax.experimental.pallas.ops.tpu.flash_attention); the backward
is two Pallas kernels (dQ gridded over q-blocks, dK/dV gridded over
k-blocks) that recompute probabilities from the saved logsumexp and
compute delta = rowsum(dO·O) in-kernel from the saved output — O(T·block)
memory, no (B,H,T,T) temporaries, all matmuls on the MXU in the storage
dtype.  On non-TPU backends the kernels run in interpret mode (CI
exercises the same code paths).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ray_tpu.ops.attention import NEG_INF

DEFAULT_BLOCK = 128
LANES = 128  # minor-dim replication for row statistics (Mosaic tiling)


def _expand_rows(stat: jax.Array, n: int) -> jax.Array:
    """(rows, LANES) lane-replicated stats → (rows, n) for elementwise use
    against an (rows, n) score tile."""
    if n % LANES == 0:
        return jnp.tile(stat, (1, n // LANES))
    return jnp.broadcast_to(stat[:, :1], (stat.shape[0], n))


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *lse_out, block_q: int,
                  block_k: int, seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    # Keep q/k/v in their storage dtype (bf16) for the MXU — f32 inputs
    # would quarter matmul throughput; accumulation stays f32 via
    # preferred_element_type.  The scale folds into f32 scores.
    q = q_ref[0]                                      # (block_q, D) bf16
    D = q.shape[-1]

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF)
    l0 = jnp.zeros((block_q,), jnp.float32)
    # Causal: block row qi only attends K blocks 0..qi (block_q == block_k).
    nblocks = seq_len // block_k
    upper = jnp.minimum(qi + 1, nblocks) if causal else nblocks
    acc, m, l = lax.fori_loop(0, upper, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    if lse_out:                                       # vjp forward only
        lse = m + jnp.log(l)                          # (block_q,)
        lse_out[0][0] = jnp.broadcast_to(lse[:, None], (block_q, LANES))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
                   block_q: int, block_k: int, seq_len: int, causal: bool,
                   scale: float):
    qi = pl.program_id(1)
    q = q_ref[0]                                      # (block_q, D)
    do = do_ref[0]
    lse = lse_ref[0]                                  # (block_q, LANES) f32
    # delta_i = rowsum(dO_i · O_i), computed here from the saved output —
    # no separate lane-replicated delta tensor in HBM.
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=-1)                          # (block_q,)
    D = q.shape[-1]

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - _expand_rows(lse, block_k))   # (block_q, block_k)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nblocks = seq_len // block_k
    upper = jnp.minimum(qi + 1, nblocks) if causal else nblocks
    dq = lax.fori_loop(0, upper, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                    dv_ref, *, block_q: int, block_k: int, seq_len: int,
                    causal: bool, scale: float):
    kj = pl.program_id(1)
    k = k_ref[0]                                      # (block_k, D)
    v = v_ref[0]
    D = k.shape[-1]

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        o = o_ref[0, pl.ds(i * block_q, block_q), :]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                      # (block_q,)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - _expand_rows(lse, block_k))   # (block_q, block_k)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    nblocks = seq_len // block_q
    # Causal: k block kj is only seen by q blocks i ≥ kj (block_q==block_k).
    lower = jnp.minimum(kj, nblocks) if causal else 0
    dk, dv = lax.fori_loop(
        lower, nblocks, body,
        (jnp.zeros((block_k, D), jnp.float32),
         jnp.zeros((block_k, D), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flatten(x):
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unflatten(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _resolve(block_size, T, interpret):
    if block_size is None:
        block_size = pick_block_size(T)
    bs = min(block_size, T)
    if T % bs:
        raise ValueError(f"seq len {T} not divisible by block {bs}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return bs, interpret


def _flash_forward_lse(q, k, v, *, causal: bool, block_size: int,
                       interpret: Optional[bool], want_lse: bool = True):
    """``want_lse=False`` (the primal / inference path) skips computing
    and writing the lane-replicated lse tensor — it is only a residual
    for the fused backward, and Pallas cannot DCE a declared output."""
    B, T, H, D = q.shape
    bs, interpret = _resolve(block_size, T, interpret)
    scale = 1.0 / math.sqrt(D)
    # (B,T,H,D) -> (B*H, T, D): one grid row per (batch, head).
    qf, kf, vf = _flatten(q), _flatten(k), _flatten(v)
    kernel = functools.partial(_flash_kernel, block_q=bs, block_k=bs,
                               seq_len=T, causal=causal, scale=scale)
    out_specs = [pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, T, D), q.dtype)]
    if want_lse:
        out_specs.append(
            pl.BlockSpec((1, bs, LANES), lambda bh, qi: (bh, qi, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, T, LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(B * H, T // bs),
        in_specs=[
            pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    out, lse = res if want_lse else (res[0], None)
    return _unflatten(out, B, H), lse


def _flash_backward(q, k, v, out, lse, g, *, causal: bool, block_size: int,
                    interpret: Optional[bool]):
    B, T, H, D = q.shape
    bs, interpret = _resolve(block_size, T, interpret)
    scale = 1.0 / math.sqrt(D)
    qf, kf, vf = _flatten(q), _flatten(k), _flatten(v)
    of = _flatten(out)
    dof = _flatten(g.astype(q.dtype))

    common = dict(block_q=bs, block_k=bs, seq_len=T, causal=causal,
                  scale=scale)
    qspec = pl.BlockSpec((1, bs, D), lambda bh, i: (bh, i, 0))
    fullspec = pl.BlockSpec((1, T, D), lambda bh, i: (bh, 0, 0))
    lsespec = pl.BlockSpec((1, bs, LANES), lambda bh, i: (bh, i, 0))
    lsefull = pl.BlockSpec((1, T, LANES), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * H, T // bs),
        in_specs=[qspec, fullspec, fullspec, qspec, qspec, lsespec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, of, dof, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B * H, T // bs),
        in_specs=[fullspec, qspec, qspec, fullspec, fullspec, lsefull],
        out_specs=[qspec, qspec],
        out_shape=[jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, T, D), v.dtype)],
        interpret=interpret,
    )(qf, kf, vf, of, dof, lse)

    return (_unflatten(dq, B, H).astype(q.dtype),
            _unflatten(dk, B, H).astype(k.dtype),
            _unflatten(dv, B, H).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_size: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """(B,T,H,D)×3 → (B,T,H,D) tiled attention; differentiable.

    ``block_size=None`` (default) resolves via ``pick_block_size`` — the
    measured-fastest tile for the sequence length — so every caller gets
    the tuned configuration without opting in."""
    out, _ = _flash_forward_lse(q, k, v, causal=causal,
                                block_size=block_size, interpret=interpret,
                                want_lse=False)
    return out


def _fwd(q, k, v, causal, block_size, interpret):
    out, lse = _flash_forward_lse(q, k, v, causal=causal,
                                  block_size=block_size, interpret=interpret)
    # Name the backward residuals so a jax.checkpoint policy
    # (save_only_these_names, models/gpt2.py remat_policy="attn") can pin
    # them across the remat boundary: saving out+lse (~52MB + ~200MB per
    # GPT-2-small layer at b32/s1024) lets the rematerialized backward skip
    # re-running the whole flash forward kernel — the single largest
    # recompute in the step.
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_attn_out")
    lse = checkpoint_name(lse, "flash_attn_lse")
    return out, (q, k, v, out, lse)


def _bwd(causal, block_size, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal=causal,
                           block_size=block_size, interpret=interpret)


flash_attention.defvjp(_fwd, _bwd)


def pick_block_size(T: int) -> int:
    """Largest block in {512, 256, 128} dividing T.  Measured on v5e
    (benchmarks/attention_bench.py --seqs 1024 --tokens 32768): fwd+bwd
    per-call 33.6/25.0/21.5 ms at blocks 128/256/512 — bigger q/k tiles
    amortize the per-grid-step VPU chain (mask iota, exp, rescale) and
    feed the MXU (block, D)x(D, block) dots with fuller tiles."""
    for bs in (512, 256, 128):
        if T % bs == 0:
            return bs
    return min(T, DEFAULT_BLOCK)


def flash_attention_for_model(q, k, v, cfg=None, **_):
    """Model hook (``attn_impl='flash'``)."""
    return flash_attention(q, k, v, True)
