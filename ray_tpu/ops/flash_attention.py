"""Pallas flash attention (TPU kernel) with a recompute backward.

Greenfield TPU component (SURVEY.md §5.7): tiled online-softmax attention
that never materializes the T×T score matrix in HBM.  Each grid step owns
one (batch·head, q-block) tile in VMEM and streams K/V blocks through the
MXU with running (m, l, acc) accumulators — the classic flash schedule,
expressed the Pallas way (grid + BlockSpecs; see
/opt/skills/guides/pallas_guide.md).

Differentiation: the forward runs the Pallas kernel; the backward
recomputes attention with the pure-JAX blockwise implementation
(``ray_tpu.ops.attention.blockwise_attention``) and differentiates that —
numerically identical softmax, O(T·block) memory, no hand-written bwd
kernel to maintain.  On non-TPU backends the kernel runs in interpret mode
(CI exercises the same code path).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ray_tpu.ops.attention import NEG_INF, blockwise_attention

DEFAULT_BLOCK = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    # Keep q/k/v in their storage dtype (bf16) for the MXU — f32 inputs
    # would quarter matmul throughput; accumulation stays f32 via
    # preferred_element_type.  The scale folds into f32 scores.
    q = q_ref[0]                                      # (block_q, D) bf16
    D = q.shape[-1]

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF)
    l0 = jnp.zeros((block_q,), jnp.float32)
    # Causal: block row qi only attends K blocks 0..qi (block_q == block_k).
    nblocks = seq_len // block_k
    upper = jnp.minimum(qi + 1, nblocks) if causal else nblocks
    acc, _, l = lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_size: int,
                   interpret: Optional[bool]) -> jax.Array:
    B, T, H, D = q.shape
    bs = min(block_size, T)
    if T % bs:
        raise ValueError(f"seq len {T} not divisible by block {bs}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / math.sqrt(D)
    # (B,T,H,D) -> (B*H, T, D): one grid row per (batch, head).
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kernel = functools.partial(_flash_kernel, block_q=bs, block_k=bs,
                               seq_len=T, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // bs),
        in_specs=[
            pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_size: int = DEFAULT_BLOCK,
                    interpret: Optional[bool] = None) -> jax.Array:
    """(B,T,H,D)×3 → (B,T,H,D) tiled attention; differentiable."""
    return _flash_forward(q, k, v, causal=causal, block_size=block_size,
                          interpret=interpret)


def _fwd(q, k, v, causal, block_size, interpret):
    out = _flash_forward(q, k, v, causal=causal, block_size=block_size,
                         interpret=interpret)
    return out, (q, k, v)


def _bwd(causal, block_size, interpret, res, g):
    q, k, v = res
    # Recompute-and-differentiate through the blockwise flash (remat-style):
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, block_size=block_size), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def flash_attention_for_model(q, k, v, cfg=None, **_):
    """Model hook (``attn_impl='flash'``)."""
    return flash_attention(q, k, v, True)
