"""Paged decode attention: one query token attending over a block table.

Reference design: PagedAttention (Kwon et al., SOSP '23 / vLLM) — the KV
cache of a running sequence is not one contiguous region but a list of
fixed-size *blocks* owned by an allocator; attention reads through a
per-sequence **block table** (block indices into a shared pool).  The
engine (``ray_tpu/serve/llm``) keeps the pool in a shared-memory segment
so prefill/decode replicas and the data plane see the same bytes.

This module is the math: a jit-friendly gather-then-attend decode kernel
over ``(num_blocks, block_size, n_kv, d)`` pools.  On the CPU rig (and
for moderate context lengths on TPU) XLA fuses the gather + matmul chain
well; the long-context TPU path would drop the same signature into a
Pallas kernel that walks the table block-by-block in VMEM (the
``ops/flash_attention.py`` machinery) — the call-site contract here is
written so that swap is local to this file.

Accumulators are float32 regardless of input dtype (bf16-safe softmax),
matching ``ops/attention.py``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

NEG_INF = jnp.finfo(jnp.float32).min


def gather_kv(pool: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Materialize each sequence's paged KV as a padded dense view.

    pool: (num_blocks, block_size, n_kv, d) — the shared block pool.
    block_tables: (B, max_blocks) int32 — indices into the pool; entries
        past a sequence's allocation may be arbitrary valid indices
        (masking is by context length, not by table entry).

    Returns (B, max_blocks * block_size, n_kv, d).
    """
    n, bs, kv, d = pool.shape
    b, mb = block_tables.shape
    g = jnp.take(pool, block_tables.reshape(-1), axis=0)
    return g.reshape(b, mb * bs, kv, d)


def paged_attention_decode(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, block_tables: jax.Array,
                           ctx_lens: jax.Array, k_new: jax.Array,
                           v_new: jax.Array) -> jax.Array:
    """Single-token decode attention through a block table.

    q:       (B, H, D)        — query for the token being decoded.
    k_pool:  (N, bs, KV, D)   — shared key pool (this layer's view).
    v_pool:  (N, bs, KV, D)   — shared value pool.
    block_tables: (B, MAXB) int32.
    ctx_lens: (B,) int32      — tokens already IN the pool per sequence
                                (the new token is not in the pool yet).
    k_new, v_new: (B, KV, D)  — this token's key/value, attended in
                                explicitly so the pool stays read-only
                                inside the step (the engine writes it
                                back to the shm block after the step).

    Returns (B, H, D) in q.dtype.
    """
    b, h, d = q.shape
    kvh = k_pool.shape[2]
    scale = 1.0 / math.sqrt(d)
    k_ctx = gather_kv(k_pool, block_tables)          # (B, T, KV, D)
    v_ctx = gather_kv(v_pool, block_tables)
    t = k_ctx.shape[1]
    if kvh != h:                                     # grouped-query heads
        rep = h // kvh
        k_ctx = jnp.repeat(k_ctx, rep, axis=2)
        v_ctx = jnp.repeat(v_ctx, rep, axis=2)
        k_new = jnp.repeat(k_new, rep, axis=1)
        v_new = jnp.repeat(v_new, rep, axis=1)
    logits = jnp.einsum("bhd,bkhd->bhk", q, k_ctx,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(t)[None, :] < ctx_lens[:, None]      # (B, T)
    logits = jnp.where(valid[:, None, :], logits, NEG_INF)
    self_logit = jnp.einsum("bhd,bhd->bh", q, k_new,
                            preferred_element_type=jnp.float32) * scale
    logits = jnp.concatenate([logits, self_logit[..., None]], axis=-1)
    probs = jax.nn.softmax(logits, axis=-1)                 # f32
    out = jnp.einsum("bhk,bkhd->bhd", probs[..., :-1],
                     v_ctx.astype(jnp.float32))
    out = out + probs[..., -1][..., None] * v_new.astype(jnp.float32)
    return out.astype(q.dtype)
