"""Attention primitives: streaming-softmax (flash-style) building blocks.

The reference framework (Ray) contains no kernels at all (SURVEY.md §5.7);
these are greenfield TPU-first components.  This module holds the
single-device pieces:

- ``flash_update``: the online-softmax block update shared by blockwise,
  ring (``ray_tpu.ops.ring_attention``) and Ulysses attention.
- ``blockwise_attention``: memory-efficient causal attention via
  ``lax.scan`` over KV blocks — O(T·block) activation memory instead of
  O(T²), differentiable by autodiff, XLA keeps the block matmuls on the MXU.

Accumulators are float32 regardless of input dtype (bf16-safe softmax).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = jnp.finfo(jnp.float32).min


def flash_update(o: jax.Array, m: jax.Array, l: jax.Array,
                 q: jax.Array, k: jax.Array, v: jax.Array,
                 mask: Optional[jax.Array],
                 scale: float) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One online-softmax accumulation step.

    Shapes: q (B,Tq,H,D); k,v (B,Tk,H,D); o (B,H,Tq,D) f32;
    m,l (B,H,Tq) f32; mask broadcastable to (B,H,Tq,Tk) bool (True=keep).

    Rows with no valid key yet keep ``m == NEG_INF``; callers must ensure
    the FIRST block every row sees has at least one valid key (causal ring
    starts with the diagonal block) so ``m`` is finite before fully-masked
    blocks contribute exp(NEG_INF - m) == 0.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o = o * corr[..., None] + pv
    return o, m_new, l


def flash_finalize(o: jax.Array, l: jax.Array, dtype) -> jax.Array:
    """(B,H,T,D) f32 accumulators → (B,T,H,D) normalized output."""
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(dtype)


def causal_mask(q_pos: jax.Array, k_pos: jax.Array) -> jax.Array:
    """(Tq,), (Tk,) global positions → (Tq, Tk) bool keep-mask."""
    return q_pos[:, None] >= k_pos[None, :]


@partial(jax.jit, static_argnames=("causal", "block_size"))
def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        *, causal: bool = True,
                        block_size: int = 512) -> jax.Array:
    """Memory-efficient attention. (B,T,H,D)×3 → (B,T,H,D).

    Scans KV in blocks with online softmax; with an outer ``jax.checkpoint``
    this is the long-sequence single-device path (activation memory
    O(B·H·T·D), never O(T²)).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bs = min(block_size, Tk)
    if Tk % bs:
        raise ValueError(f"kv length {Tk} not divisible by block {bs}")
    scale = 1.0 / math.sqrt(D)
    nblocks = Tk // bs
    kb = k.reshape(B, nblocks, bs, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nblocks, bs, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(Tq)

    o0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), NEG_INF)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)

    def body(carry, xs):
        o, m, l = carry
        i, kblk, vblk = xs
        if causal:
            k_pos = i * bs + jnp.arange(bs)
            mask = causal_mask(q_pos, k_pos)[None, None]
        else:
            mask = None
        o, m, l = flash_update(o, m, l, q, kblk, vblk, mask, scale)
        return (o, m, l), None

    # Forward block order satisfies flash_update's masking contract for
    # causal attention: block 0 contains k=0, a valid key for every row.
    idx = jnp.arange(nblocks)
    (o, _, l), _ = lax.scan(body, (o0, m0, l0), (idx, kb, vb))
    return flash_finalize(o, l, q.dtype)


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    *, causal: bool = True,
                    q_offset: int | jax.Array = 0) -> jax.Array:
    """Plain O(T²) attention (B,T,H,D); the XLA-fused short-sequence path.

    ``q_offset`` shifts query positions for causal masking when q is a
    chunk of a longer sequence (used by decode / chunked prefill).
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        mask = causal_mask(q_pos, jnp.arange(k.shape[1]))
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
