"""ray_tpu.ops — TPU kernels and long-context attention (SURVEY.md §5.7).

The reference framework ships no kernels; these are greenfield TPU-first
components: flash/blockwise attention, a Pallas flash kernel, the two
context-parallel schedules (ring via ppermute, Ulysses via all-to-all),
and the decomposed collective matmuls that hide model-parallel
all-gather/reduce-scatter legs behind chunked compute (DESIGN.md §4m).
"""

from ray_tpu.ops.attention import (  # noqa: F401
    blockwise_attention, dense_attention,
)
from ray_tpu.ops.collective_matmul import (  # noqa: F401
    all_gather_matmul, matmul_reduce_scatter, ring_scan,
)
from ray_tpu.ops.flash_attention import flash_attention  # noqa: F401
from ray_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention_decode,
)
from ray_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_sharded,
)
from ray_tpu.ops.ulysses import (  # noqa: F401
    ulysses_attention, ulysses_attention_sharded,
)

__all__ = [
    "dense_attention", "blockwise_attention", "flash_attention",
    "all_gather_matmul", "matmul_reduce_scatter", "ring_scan",
    "paged_attention_decode",
    "ring_attention", "ring_attention_sharded",
    "ulysses_attention", "ulysses_attention_sharded",
]
