"""Ring attention: context-parallel attention over an ICI ring.

Greenfield TPU component (SURVEY.md §5.7 — the reference has no sequence
parallelism).  The sequence axis is sharded over the ``context`` mesh axis;
each device holds a contiguous chunk of Q/K/V.  K/V blocks rotate around
the ring via ``lax.ppermute`` (XLA lowers this to ICI collective-permute,
overlapping the transfer of step s+1's block with step s's compute), while
each device accumulates its queries' attention with the online-softmax
update from ``ray_tpu.ops.attention``.

Activation memory per device is O(T_local·D); the full T×T score matrix is
never materialized anywhere.  Differentiable end-to-end: ``lax.scan`` +
``ppermute`` both have transpose rules, so reverse-mode runs the ring
backwards automatically.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import (causal_mask, dense_attention,
                                   flash_finalize, flash_update)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, axis_size: int,
                   causal: bool = True) -> jax.Array:
    """Per-shard ring attention; call inside shard_map.

    q/k/v: (B, T_local, H, D) — this device's contiguous sequence chunk;
    chunk index = ``lax.axis_index(axis_name)``.  Returns (B, T_local, H, D).
    """
    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    me = lax.axis_index(axis_name)
    q_pos = me * T + jnp.arange(T)

    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T), jnp.finfo(jnp.float32).min)
    l0 = jnp.zeros((B, H, T), jnp.float32)

    def body(step, carry, kv):
        o, m, l = carry
        kc, vc = kv
        # Step s processes chunk (me - s) mod n: step 0 is the diagonal
        # block, which always has a valid key for every row (causal q>=k
        # includes self) — the flash_update masking contract.
        src = (me - step) % axis_size
        if causal:
            k_pos = src * T + jnp.arange(T)
            mask = causal_mask(q_pos, k_pos)[None, None]
        else:
            mask = None
        return flash_update(o, m, l, q, kc, vc, mask, scale)

    # ring_scan issues each rotation BEFORE the update consuming the
    # resident chunk, so XLA pipelines transfer s+1 under compute s (the
    # same double-buffer schedule ops/collective_matmul.py rides).
    from ray_tpu.ops.collective_matmul import ring_scan
    o, _, l = ring_scan(body, (o0, m0, l0), (k, v),
                        axis_name=axis_name, axis_size=axis_size)
    return flash_finalize(o, l, q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mesh, axis_name: str = "context",
                           batch_axes=("data", "fsdp"),
                           head_axis: Optional[str] = "tensor",
                           causal: bool = True) -> jax.Array:
    """GSPMD-land wrapper: global (B,T,H,D) arrays → shard_map ring.

    Inputs are (re)sharded to [batch_axes, context, head_axis, None]; the
    ring runs over ICI neighbors of the ``context`` axis.
    """
    axis_size = mesh.shape[axis_name]
    if axis_size == 1:
        return dense_attention(q, k, v, causal=causal)
    spec = P(tuple(a for a in batch_axes if a in mesh.shape), axis_name,
             head_axis if head_axis in mesh.shape else None, None)
    inner = partial(ring_attention, axis_name=axis_name,
                    axis_size=axis_size, causal=causal)
    from ray_tpu._private.jax_compat import shard_map
    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ring_attention_for_model(q, k, v, cfg=None, *,
                             axis_name: Optional[str] = "context"):
    """Model hook (``GPT2Config.attn_impl='ring'``): mesh comes from the
    ambient program mesh set by ``ray_tpu.parallel.spmd``."""
    from ray_tpu.parallel import mesh as mesh_lib
    axis_name = axis_name or "context"
    mesh = mesh_lib.get_ambient_mesh()
    if mesh is None or axis_name not in mesh.shape \
            or mesh.shape[axis_name] == 1:
        return dense_attention(q, k, v, causal=True)
    return ring_attention_sharded(q, k, v, mesh=mesh, axis_name=axis_name,
                                  causal=True)
