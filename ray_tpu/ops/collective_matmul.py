"""Decomposed sharded matmuls: collective legs hidden behind compute.

The MFU plateau (BENCH_r04→r05: 0.505→0.508 with ``mfu_vs_delivered``
0.64) is unoverlapped collectives: GSPMD materializes a model-parallel
matmul as ``all-gather → one big matmul`` or ``one big matmul → psum /
reduce-scatter``, and the collective leg serializes against the compute
it feeds.  The fix (Wang et al. 2023, "Overlap Communication with
Dependent Computation via Decomposition") is to decompose both shapes
into chunked ``lax.ppermute`` rings — the machinery already proven by
``ops/ring_attention.py`` — so chunk s+1's transfer rides ICI while
chunk s's partial product is on the MXU:

- :func:`all_gather_matmul` — ``Y = allgather(X) @ W`` without ever
  materializing ``allgather(X)``: each ring step matmuls the resident
  X chunk against the local W shard while the next chunk is in flight.
- :func:`matmul_reduce_scatter` — ``Y = reducescatter(X @ W)`` without
  ever materializing the full partial product: the accumulator rotates
  around the ring and each device adds its partial for the chunk
  currently passing through, computed while the accumulator was in
  flight.

Both carry custom VJPs so reverse-mode overlaps the same way: the two
primitives are each other's transpose (d/dX of all-gather-matmul IS a
matmul-reduce-scatter, and vice versa), and the dW reductions run as
one more ring.  Everything is ``lax.scan`` + ``ppermute``, so the pair
nests inside ``shard_map`` / ``jax.checkpoint`` / ``lax.scan`` layers
exactly like ring attention does.

These are PER-SHARD primitives: call inside ``shard_map`` with
``axis_name`` bound.  ``ray_tpu/models/gpt2.py`` routes the qkv /
attn-out / MLP projections through them when the ambient mesh has a
model axis (``seq`` or ``tensor`` > 1).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def ring_scan(body: Callable[[Any, Any, Any], Any], carry: Any,
              rotating: Any, *, axis_name: str, axis_size: int) -> Any:
    """Run ``axis_size`` steps of a ppermute ring over ``rotating``.

    ``body(step, carry, rotating) -> carry`` consumes the rotating block
    resident at this step; after step ``s`` the device holds the block
    that started ``s`` hops upstream (source index ``(me - s) % n`` for
    the canonical ``d → d+1`` ring).  The rotation for step s+1 is
    issued BEFORE body runs, so it carries no data dependence on body's
    compute and XLA's latency-hiding scheduler overlaps the transfer
    with the matmul/attention work (double buffering).  The final
    rotation is redundant in exact arithmetic but kept so every step is
    the same program — the shape XLA software-pipelines.
    """
    perm = [(d, (d + 1) % axis_size) for d in range(axis_size)]

    def scan_body(c, step):
        inner, rot = c
        rot_next = jax.tree_util.tree_map(
            lambda a: lax.ppermute(a, axis_name, perm), rot)
        inner = body(step, inner, rot)
        return (inner, rot_next), None

    (carry, _), _ = lax.scan(scan_body, (carry, rotating),
                             jnp.arange(axis_size))
    return carry


def _chunk(x: jax.Array, i, t: int) -> jax.Array:
    """Rows ``[i*t, (i+1)*t)`` of x's second-to-last dim (traced i ok)."""
    return lax.dynamic_slice_in_dim(x, i * t, t, axis=-2)


def _put_chunk(out: jax.Array, y: jax.Array, i, t: int) -> jax.Array:
    return lax.dynamic_update_slice_in_dim(out, y, i * t, axis=-2)


def _xt_dot(x: jax.Array, g: jax.Array) -> jax.Array:
    """dW partial: contract x (..., t, k) with g (..., t, n) over every
    dim but the last → (k, n) f32."""
    kdim, ndim = x.shape[-1], g.shape[-1]
    xf = x.reshape(-1, kdim)
    gf = g.reshape(-1, ndim)
    return jax.lax.dot_general(xf, gf, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# all-gather-matmul:  Y = allgather_rows(X) @ W
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def all_gather_matmul(x: jax.Array, w: jax.Array, axis_name: str,
                      axis_size: int) -> jax.Array:
    """x (..., t, k) local rows; w (k, n) local columns →
    (..., t*axis_size, n): the full gathered row space times this
    device's W shard, gather hidden behind the chunk matmuls."""
    return _ag_matmul_fwd_impl(x, w, axis_name, axis_size)


def _ag_matmul_fwd_impl(x, w, axis_name, axis_size):
    if axis_size == 1:
        return x @ w
    t = x.shape[-2]
    me = lax.axis_index(axis_name)
    out = jnp.zeros(x.shape[:-2] + (t * axis_size, w.shape[-1]),
                    jnp.result_type(x.dtype, w.dtype))

    def body(step, out, xc):
        src = (me - step) % axis_size
        return _put_chunk(out, xc @ w, src, t)

    return ring_scan(body, out, x, axis_name=axis_name,
                     axis_size=axis_size)


def _ag_matmul_fwd(x, w, axis_name, axis_size):
    return _ag_matmul_fwd_impl(x, w, axis_name, axis_size), (x, w)


def _ag_matmul_bwd(axis_name, axis_size, res, g):
    x, w = res
    # dX: every device's W shard saw every X chunk, so chunk j's grad is
    # Σ over devices of g[chunk j] @ Wᵀ — exactly a matmul-reduce-scatter
    # (the transpose ring overlaps the same way the forward did).
    dx = _mm_rs_fwd_impl(g, w.T, axis_name, axis_size,
                         acc_dtype=jnp.float32).astype(x.dtype)
    if axis_size == 1:
        dw = _xt_dot(x, g).astype(w.dtype)
        return dx, dw
    # dW = gathered(X)ᵀ @ g: one more ring over the X chunks, each step
    # contracting the resident chunk with its rows of g while the next
    # chunk is in flight.
    t = x.shape[-2]
    me = lax.axis_index(axis_name)
    dw0 = jnp.zeros(w.shape, jnp.float32)

    def body(step, dw, xc):
        src = (me - step) % axis_size
        return dw + _xt_dot(xc, _chunk(g, src, t))

    dw = ring_scan(body, dw0, x, axis_name=axis_name, axis_size=axis_size)
    return dx, dw.astype(w.dtype)


all_gather_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


# ---------------------------------------------------------------------------
# matmul-reduce-scatter:  Y = reducescatter_rows(X @ W)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_reduce_scatter(x: jax.Array, w: jax.Array, axis_name: str,
                          axis_size: int) -> jax.Array:
    """x (..., t*axis_size, k) full rows of this device's partial
    operand; w (k, n) → (..., t, n): rows chunk-summed across the ring,
    this device keeping chunk ``axis_index``.  The psum/reduce-scatter
    leg never exists as one collective: partial chunks are computed
    while the accumulator is in flight."""
    return _mm_rs_fwd_impl(x, w, axis_name, axis_size)


def _mm_rs_fwd_impl(x, w, axis_name, axis_size, acc_dtype=jnp.float32):
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    if axis_size == 1:
        return (x @ w).astype(out_dtype)
    n = axis_size
    t = x.shape[-2] // n
    me = lax.axis_index(axis_name)
    perm = [(d, (d + 1) % n) for d in range(n)]

    # Chunk c is born at device c+1 (its partial, no add), rides the ring
    # through c+2 … and ends at device c having accumulated every
    # device's partial: at step s, device d adds its partial for chunk
    # (d - 1 - s) % n.  The ppermute for step s is issued before step
    # s's partial matmul, so transfer and compute overlap.
    acc = (_chunk(x, (me - 1) % n, t) @ w).astype(acc_dtype)

    def body(carry, step):
        acc = carry
        acc_in = lax.ppermute(acc, axis_name, perm)
        part = _chunk(x, (me - 1 - step) % n, t) @ w
        return acc_in + part.astype(acc_dtype), None

    acc, _ = lax.scan(body, acc, jnp.arange(1, n))
    return acc.astype(out_dtype)


def _mm_rs_fwd(x, w, axis_name, axis_size):
    return _mm_rs_fwd_impl(x, w, axis_name, axis_size), (x, w)


def _mm_rs_bwd(axis_name, axis_size, res, g):
    x, w = res
    # dX: the full row space re-materializes from the per-device chunk
    # grads times Wᵀ — exactly an all-gather-matmul.
    dx = _ag_matmul_fwd_impl(g, w.T, axis_name, axis_size).astype(x.dtype)
    if axis_size == 1:
        return dx, _xt_dot(x, g).astype(w.dtype)
    # dW = Xᵀ @ gathered(g): rotate the local chunk grad around the ring,
    # each step contracting it with the matching rows of X.
    t = g.shape[-2]
    me = lax.axis_index(axis_name)
    dw0 = jnp.zeros(w.shape, jnp.float32)

    def body(step, dw, gc):
        src = (me - step) % axis_size
        return dw + _xt_dot(_chunk(x, src, t), gc)

    dw = ring_scan(body, dw0, g, axis_name=axis_name, axis_size=axis_size)
    return dx, dw.astype(w.dtype)


matmul_reduce_scatter.defvjp(_mm_rs_fwd, _mm_rs_bwd)


# ---------------------------------------------------------------------------
# Reference (un-decomposed) implementations: the numerics oracle for the
# tests and the A/B baseline for bench overlap accounting.
# ---------------------------------------------------------------------------

def all_gather_matmul_reference(x: jax.Array, w: jax.Array,
                                axis_name: str,
                                axis_size: int) -> jax.Array:
    """The GSPMD shape being decomposed: one all-gather, one matmul."""
    if axis_size == 1:
        return x @ w
    xg = lax.all_gather(x, axis_name, axis=-2, tiled=True)
    return xg @ w


def matmul_reduce_scatter_reference(x: jax.Array, w: jax.Array,
                                    axis_name: str,
                                    axis_size: int) -> jax.Array:
    """One matmul, one psum_scatter — the serialized collective leg."""
    y = x @ w
    if axis_size == 1:
        return y
    return lax.psum_scatter(y, axis_name, scatter_dimension=y.ndim - 2,
                            tiled=True)


def model_parallel_sizes(mesh) -> Tuple[int, int]:
    """(seq, tensor) axis sizes of a mesh (1 when absent) — the gate the
    model layer uses to decide whether the decomposed path is live."""
    shape = dict(getattr(mesh, "shape", {}) or {})
    return int(shape.get("seq", 1)), int(shape.get("tensor", 1))
