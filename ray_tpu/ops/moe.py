"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

Reference contrast (SURVEY.md §2.4): Ray core has no MoE/expert parallelism —
"EP" in its ecosystem is user code (DeepSpeed-MoE) inside Train worker actors,
with NCCL all-to-alls the framework never sees.  Here EP is a first-class op:
expert weights carry a leading ``num_experts`` axis sharded
``P("expert", ...)``, token dispatch/combine are einsums against one-hot
dispatch tensors, and GSPMD lowers the resulting resharding to all-to-alls
over ICI.  No shard_map needed — the op stays in automatic-sharding land so
it composes with dp/fsdp/tp on the same mesh.

Design follows the GShard/Switch dispatch formulation (public): top-k gating
with an auxiliary load-balancing loss, fixed expert capacity with token
dropping, einsum-based dispatch/combine (MXU-friendly — the dispatch tensors
are the only non-matmul cost and XLA fuses their construction).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class MoEMetrics(NamedTuple):
    aux_loss: jax.Array       # load-balance loss (scalar)
    router_z_loss: jax.Array  # logit magnitude regularizer (scalar)
    fraction_dropped: jax.Array


def expert_capacity(num_tokens: int, num_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots; multiple of 8 for TPU-friendly tiling."""
    cap = int(math.ceil(k * num_tokens * capacity_factor / num_experts))
    return max(8, -(-cap // 8) * 8)


def topk_router(x: jax.Array, w_router: jax.Array, k: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token → expert assignment.

    x: (N, d) tokens; w_router: (d, E).  Returns (gates (N,E) with zeros off
    the top-k, logits (N,E), topk_idx (N,k)).  float32 softmax for stability
    regardless of activation dtype.
    """
    logits = jnp.asarray(x, jnp.float32) @ jnp.asarray(w_router, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topk_vals, topk_idx = jax.lax.top_k(probs, k)
    gates = jnp.zeros_like(probs)
    gates = jnp.put_along_axis(gates, topk_idx, topk_vals, axis=-1,
                               inplace=False)
    # renormalize the kept mass so combine weights sum to 1 per token
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    return gates, logits, topk_idx


def _dispatch_tensors(gates: jax.Array, capacity: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build (dispatch (N,E,C) bool, combine (N,E,C) float, dropped (N,))
    from gate weights.  Position within an expert is assignment order
    (cumsum over tokens); tokens past capacity are dropped.
    """
    N, E = gates.shape
    assigned = gates > 0.0                                   # (N, E)
    # position of each token in each expert's queue (0-based)
    pos = jnp.cumsum(assigned.astype(jnp.int32), axis=0) - 1  # (N, E)
    keep = assigned & (pos < capacity)
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, -1), capacity,
                            dtype=gates.dtype)               # (N, E, C)
    dispatch = pos_oh
    combine = pos_oh * gates[..., None]
    dropped = assigned.any(-1) & ~keep.any(-1)
    return dispatch, combine, dropped


def load_balance_loss(gates: jax.Array, logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Switch-style aux loss: E * <fraction_tokens_e> · <mean_prob_e>, plus
    router z-loss penalizing logit magnitude."""
    E = gates.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = (gates > 0).astype(jnp.float32).mean(0)    # (E,)
    mean_prob = probs.mean(0)                                # (E,)
    aux = E * jnp.sum(frac_tokens * mean_prob)
    z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return aux, z


def moe_ffn(x: jax.Array,
            w_router: jax.Array,
            w_in: jax.Array,
            w_out: jax.Array,
            *,
            k: int = 2,
            capacity_factor: float = 1.25,
            activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu
            ) -> Tuple[jax.Array, MoEMetrics]:
    """Expert-parallel feed-forward block.

    x: (B, S, d).  w_router: (d, E).  w_in: (E, d, ff).  w_out: (E, ff, d) —
    the leading E axis is the one sharded over the ``expert`` mesh axis (see
    ``MOE_RULES``); the two dispatch einsums below are where GSPMD inserts
    the token all-to-alls.
    """
    B, S, d = x.shape
    E = w_router.shape[-1]
    N = B * S
    tokens = x.reshape(N, d)
    gates, logits, _ = topk_router(tokens, w_router, k)
    cap = expert_capacity(N, E, k, capacity_factor)
    dispatch, combine, dropped = _dispatch_tensors(gates, cap)

    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)  # a2a in
    h = activation(jnp.einsum("ecd,edf->ecf", xe, w_in))
    ye = jnp.einsum("ecf,efd->ecd", h, w_out)
    y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), ye)        # a2a out

    aux, z = load_balance_loss(gates, logits)
    metrics = MoEMetrics(aux_loss=aux, router_z_loss=z,
                         fraction_dropped=dropped.mean())
    return y.reshape(B, S, d), metrics


# Sharding rules for MoE params (compose with TRANSFORMER_RULES by
# prepending these — first match wins).
MOE_RULES = [
    # stacked-per-layer variants FIRST (first match wins, and the generic
    # patterns below would also fullmatch these paths)
    (r".*blocks/moe/router$", P("pipeline", None, None)),
    (r".*blocks/moe/w_in$",   P("pipeline", "expert", "fsdp", "tensor")),
    (r".*blocks/moe/w_out$",  P("pipeline", "expert", "tensor", "fsdp")),
    (r".*moe/router$",   P(None, None)),            # (d, E) replicated
    (r".*moe/w_in$",     P("expert", "fsdp", "tensor")),
    (r".*moe/w_out$",    P("expert", "tensor", "fsdp")),
]


def init_moe_params(rng: jax.Array, d_model: int, d_ff: int,
                    num_experts: int, dtype=jnp.float32) -> Dict[str, jax.Array]:
    kr, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": (jax.random.normal(kr, (d_model, num_experts)) * 0.02
                   ).astype(dtype),
        "w_in": (jax.random.normal(ki, (num_experts, d_model, d_ff))
                 * scale_in).astype(dtype),
        "w_out": (jax.random.normal(ko, (num_experts, d_ff, d_model))
                  * scale_out).astype(dtype),
    }
