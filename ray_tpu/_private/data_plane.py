"""Peer-to-peer object plane for multi-host clusters.

Reference: the ObjectManager's node↔node chunked transfer (PullManager /
PushManager, SURVEY.md §2.1) — data moves directly between the holder
host and the puller host; the head is only a *fallback relay* for hosts
that cannot reach each other (hub-spoke NAT topologies).

Mechanics here: each NodeAgent host keeps a **spool directory** of large
objects produced on that host (one file per object, written by the
producing worker — same host, plain file I/O) and runs a
``DataPlaneServer`` — a TCP listener (per-session HMAC auth, the same
handshake as every other socket) serving reads of those files.  The GCS
records ``loc="remote"`` + the holder node; consumers dial the holder's
advertised data address, falling back to the head relay when the dial
fails.

Transfer protocol (r7; negotiated per connection — DESIGN.md §4):

- **v1 streamed** (``fetch_stream``): ONE request.  Ranges at or below
  ``data_inline_pull_bytes`` come back inline in the ack itself — one
  message round trip, no frame-boundary syscalls (small pulls are
  syscall-bound, not copy-bound).  Above it, the server pushes the
  whole byte range as length-prefixed raw binary bulk frames
  (``wire.BULK_*``) — header ``write`` + ``os.sendfile`` from the spool
  file on a direct TCP connection, so the payload never enters
  userspace on the send side; the receiver ``recv_into``s straight into
  its pre-sized buffer.  A pull is one round trip plus line-rate
  streaming.  Through the head's message-pump relay, the same frames
  ride ``send_bytes`` messages (the pump re-frames Connection messages
  and would corrupt raw fd traffic).
- **v0 chunked** (``fetch_object`` / ``fetch_chunk``): the seed
  request-per-chunk pickled-dict protocol, kept verbatim for legacy
  peers.  A v1 puller discovers a v0 holder via the ``__proto_hello__``
  unknown-op error and degrades; a v0 puller never says hello and the
  server keeps speaking v0 to it.

Pulls go through a per-process :class:`DataPlanePool` — connections are
keyed by peer address, reused across pulls and deletes (no dial+HMAC
per object), LRU-bounded, and invalidated wholesale on a broken
connection (mirroring ``RpcPool.invalidate``).  Objects at or above
``data_stripe_threshold_bytes`` pull as N parallel range-striped
streams over pool connections.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ray_tpu._private import protocol, rtlog, wire
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util import metrics_catalog as mcat

logger = rtlog.get("data-plane")


def spool_path(spool_dir: str, object_id: str) -> Path:
    return Path(spool_dir) / f"obj_{object_id}"


def spool_capacity_bytes() -> int:
    mb = int(os.environ.get("RTPU_SPOOL_CAPACITY_MB", 0) or 0)
    if mb <= 0:
        mb = GLOBAL_CONFIG.object_store_memory_mb
    return mb * 1024 * 1024


def _admit_spool(spool_dir: str,
                 object_id: str, size: int):  # rtlint: returns(file)
    """Admission check + reservation for one spool write; returns the
    opened ``.tmp`` file (positioned at 0, reserved to ``size``).

    Admission-checked against the spool capacity (default: the object
    store capacity — an unbounded spool on a tmpfs-backed /tmp would
    OOM the host with no backpressure).  The scan is O(spooled files);
    spooled objects are large, so counts stay small.

    The scan + reservation run under a per-spool flock so N concurrent
    producers can't each pass the check and collectively overshoot the
    capacity; the reservation is an ftruncate of the .tmp file to full
    size, which later scanners count, so the bulk data copy itself
    happens outside the lock."""
    import fcntl

    cap = spool_capacity_bytes()
    path = spool_path(spool_dir, object_id)
    tmp = path.with_suffix(".tmp")
    with open(Path(spool_dir) / ".admission.lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        used = 0
        now = time.time()
        try:
            with os.scandir(spool_dir) as it:
                for e in it:
                    if e.name == ".admission.lock":
                        continue
                    try:
                        st = e.stat()
                        if e.name.endswith(".tmp") and \
                                now - st.st_mtime > 300:
                            # orphaned reservation: a writer SIGKILLed
                            # mid-write (e.g. by the per-node OOM killer)
                            # never runs its cleanup — sweep it here or it
                            # counts against capacity forever
                            os.unlink(e.path)
                            continue
                        used += st.st_size
                    except OSError:
                        pass
        except OSError:
            pass
        if used + size > cap:
            from ray_tpu.exceptions import ObjectStoreFullError
            raise ObjectStoreFullError(
                f"host spool full: {used + size} > {cap} bytes "
                f"(RTPU_SPOOL_CAPACITY_MB to raise)")
        f = open(tmp, "wb")
        try:
            f.truncate(size)  # reserve while still under the lock
        except OSError:
            pass
    return f


def _seal_spool(spool_dir: str, object_id: str, f) -> None:  # rtlint: owns(f)
    import fcntl
    f.close()
    path = spool_path(spool_dir, object_id)
    # rename under the admission flock: a concurrent admission scan
    # racing a same-directory rename can observe the entry under
    # NEITHER name (POSIX readdir gives no atomicity across a rename)
    # and under-count the spool, over-admitting past capacity
    with open(Path(spool_dir) / ".admission.lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        os.replace(path.with_suffix(".tmp"), path)


def _abort_spool(spool_dir: str, object_id: str, f) -> None:  # rtlint: owns(f)
    f.close()
    try:  # a failed write must not hold its reservation
        os.unlink(spool_path(spool_dir, object_id).with_suffix(".tmp"))
    except OSError:
        pass


def write_spool(spool_dir: str, object_id: str, wire_bytes) -> int:
    """Atomic admission-checked write of pre-assembled wire bytes."""
    size = len(wire_bytes)
    f = _admit_spool(spool_dir, object_id, size)
    try:
        f.write(wire_bytes)
        _seal_spool(spool_dir, object_id, f)
    except BaseException:
        _abort_spool(spool_dir, object_id, f)
        raise
    return size


def write_spool_value(spool_dir: str, object_id: str, pickled,
                      buffers) -> int:
    """Serialize straight into the spool file with writev — the
    producer-side single-copy path (``write_value_to_fd``): out-of-band
    buffers stream from their numpy backing into the page cache without
    first materializing the full wire bytes in this process's heap."""
    from ray_tpu._private.serialization import (serialized_size,
                                                write_value_to_fd)
    size = serialized_size(pickled, buffers)
    f = _admit_spool(spool_dir, object_id, size)
    try:
        write_value_to_fd(f.fileno(), pickled, buffers)
        _seal_spool(spool_dir, object_id, f)
    except BaseException:
        _abort_spool(spool_dir, object_id, f)
        raise
    return size


class _SpoolFdCache:
    """Open spool-file fds kept hot across requests.

    Every streamed pull used to pay ``open`` + ``fstat`` + ``close`` on
    the spool file — three gofer round trips (~50 µs) on sandboxed
    kernels, a third of a warm small-pull.  Spool files are immutable
    once sealed (written as ``.tmp``, renamed into place), so the fd
    and size stay valid for the object's whole life.

    Each checkout returns a ``dup`` of the cached master fd (a pure
    fd-table operation — no path walk, no gofer), so an eviction or a
    ``delete_object`` closing the master never yanks the fd out from
    under an in-flight stream: the dup keeps the inode alive, matching
    the pull-racing-delete semantics of the uncached path."""

    def __init__(self, spool_dir: str, cap: int = 32):
        from collections import OrderedDict
        self._spool_dir = spool_dir
        self._cap = max(1, cap)
        self._lock = threading.Lock()
        # object_id -> (master fd, size), LRU order (oldest first)
        self._fds: Dict[str, tuple] = OrderedDict()  # guarded by: _lock

    def checkout(self, object_id: str):  # rtlint: returns(fd)
        """(dup'd fd, file size); the caller owns the dup and must
        close it.  Raises OSError/FileNotFoundError on a spool miss."""
        with self._lock:
            ent = self._fds.get(object_id)
            if ent is not None:
                self._fds.move_to_end(object_id)
                return os.dup(ent[0]), ent[1]
        mfd = os.open(spool_path(self._spool_dir, object_id), os.O_RDONLY)
        try:
            size = os.fstat(mfd).st_size
        except OSError:
            os.close(mfd)
            raise
        victims = []
        with self._lock:
            ent = self._fds.get(object_id)
            if ent is not None:
                # lost an insert race: keep the existing master
                self._fds.move_to_end(object_id)
                victims.append(mfd)
                dup, sz = os.dup(ent[0]), ent[1]
            else:
                self._fds[object_id] = (mfd, size)
                while len(self._fds) > self._cap:
                    _, (vfd, _) = self._fds.popitem(last=False)
                    victims.append(vfd)
                dup, sz = os.dup(mfd), size
        for v in victims:
            try:
                os.close(v)
            except OSError:
                pass
        return dup, sz

    def invalidate(self, object_id: str) -> None:
        with self._lock:
            ent = self._fds.pop(object_id, None)
        if ent is not None:
            try:
                os.close(ent[0])
            except OSError:
                pass

    def close_all(self) -> None:
        with self._lock:
            ents = list(self._fds.values())
            self._fds.clear()
        for fd, _ in ents:
            try:
                os.close(fd)
            except OSError:
                pass


class DataPlaneServer:
    """Serves reads of one host's object spool.

    Requests are framed-pickle messages (the seed wire format — both
    v0 and v1 peers speak it for control); bulk payload transport
    depends on the per-connection negotiated version:

      __proto_hello__: {versions} → {proto}       (v1 capability probe)
      fetch_object:  {object_id} → {size} | {error}
      fetch_chunk:   {object_id, offset, length} → {data}
      fetch_stream:  {object_id, offset, length, raw}
                       → {size, len, data} (range ≤ data_inline_pull_bytes)
                       | {size, len} then bulk frames (v1)
      delete_object: {object_id} → {}             (refcount hit zero)
      stats:         {} → {bytes_served, objects_served, conns_accepted}
    """

    def __init__(self, spool_dir: str, host: str = "0.0.0.0",
                 advertise_host: Optional[str] = None):
        self.spool_dir = spool_dir
        Path(spool_dir).mkdir(parents=True, exist_ok=True)
        self._listener = protocol.make_tcp_listener(host, 0)
        try:
            self.port = self._listener.address[1]
            self.advertise_addr = \
                f"tcp://{advertise_host or host}:{self.port}"
            # serving counters: one _serve thread per connection mutates
            # them, stats/tests read them — a bare += would drop updates
            self._stats_lock = threading.Lock()
            self.bytes_served = 0       # guarded by: _stats_lock
            self.objects_served = 0     # guarded by: _stats_lock
            self.conns_accepted = 0     # guarded by: _stats_lock
            self._conns: List = []      # guarded by: _stats_lock
            self._fd_cache = _SpoolFdCache(spool_dir)
            self._stop = threading.Event()
            threading.Thread(target=self._accept_loop, name="data-plane",
                             daemon=True).start()
        except BaseException:
            # a failed boot returns no server: close the bound port
            self._listener.close()
            raise

    def _accept_loop(self) -> None:
        protocol.serve_accept_loop(self._listener, self._stop.is_set,
                                   self._serve, "data-plane-serve")

    def _count_served(self, nbytes: int, obj: bool = False) -> None:
        """``obj=True`` counts one OBJECT served — the offset-0 request
        of a stream (full pull or first stripe) and the legacy
        ``fetch_object`` size probe.  Chunk and non-zero-offset stripe
        requests only add bytes, so ``objects_served`` stays an object
        count, not a request count."""
        with self._stats_lock:
            if obj:
                self.objects_served += 1
            self.bytes_served += nbytes
        if GLOBAL_CONFIG.metrics_enabled and nbytes:
            mcat.get("rtpu_data_bytes_total").inc(nbytes,
                                                  tags={"dir": "out"})

    def _serve(self, conn) -> None:
        from ray_tpu._private import flight_recorder
        protocol.tune_data_socket(conn)
        with self._stats_lock:
            self.conns_accepted += 1
            self._conns.append(conn)
        try:
            while not self._stop.is_set():
                try:
                    # rtlint: blocks-ok(parks between a puller's ops;
                    # peer death EOFs the conn — per-conn thread, peer
                    # liveness is the deadline)
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                op = msg.get("op")
                if flight_recorder.enabled():
                    flight_recorder.record(
                        "data_frame",
                        f"{op} {str(msg.get('object_id', ''))[:20]} "
                        f"off={msg.get('offset', 0)}")
                if op == "__proto_hello__":
                    try:
                        conn.send({"proto": wire.negotiate_version(
                            msg.get("versions") or [0],
                            wire.DATA_PROTO_MIN, wire.DATA_PROTO_MAX)})
                    except wire.ProtocolVersionError as e:
                        conn.send({"error": str(e)})
                    continue
                oid = msg.get("object_id", "")
                path = spool_path(self.spool_dir, oid)
                if op == "fetch_stream":
                    # handles its own errors: a mid-stream failure
                    # leaves the conn in an undefined framing state
                    if not self._serve_stream(conn, msg):
                        return
                    continue
                try:
                    if op == "fetch_object":
                        self._count_served(0, obj=True)
                        conn.send({"size": path.stat().st_size})
                    elif op == "fetch_chunk":
                        with open(path, "rb") as f:
                            data = os.pread(f.fileno(), msg["length"],
                                            msg["offset"])
                        self._count_served(len(data))
                        conn.send({"data": data})
                    elif op == "delete_object":
                        try:
                            os.unlink(path)
                        except FileNotFoundError:
                            pass
                        # in-flight streams keep their dup'd fd (the
                        # inode lives until they finish); fetches after
                        # this reply must miss
                        self._fd_cache.invalidate(oid)
                        conn.send({})
                    elif op == "stats":
                        with self._stats_lock:
                            st = {"bytes_served": self.bytes_served,
                                  "objects_served": self.objects_served,
                                  "conns_accepted": self.conns_accepted}
                        conn.send(st)
                    else:
                        conn.send({"error": f"unknown op {op!r}"})
                except FileNotFoundError:
                    conn.send({"error": "not found"})
                except OSError as e:
                    conn.send({"error": str(e)})
        finally:
            with self._stats_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    # ---------------------------------------------------------- streaming
    def _serve_stream(self, conn, msg: dict) -> bool:  # rtlint: replies
        """One fetch_stream: ack {size, len} then push bulk frames.

        Returns False when the connection is no longer in a known
        framing state (mid-stream socket/read failure) — the caller
        must close it.  Pre-stream misses reply {error} and keep the
        conn pooled."""
        from ray_tpu.util import tracing
        offset = int(msg.get("offset", 0) or 0)
        length = msg.get("length")
        raw = bool(msg.get("raw", True))
        # wire-propagated span (DATA_PROTO_TRACE peers only): the serve
        # leg becomes a child of the puller's span, tagged bytes/path
        span = tracing.extract_wire_trace(msg)
        t0 = time.time()
        try:
            fd, size = self._fd_cache.checkout(msg.get("object_id", ""))
        except OSError:
            try:
                conn.send({"error": "not found"})
                return True
            except (OSError, ValueError):
                return False
        try:
            try:
                n = size - offset if length is None or length < 0 \
                    else min(int(length), size - offset)
                n = max(n, 0)
                if n <= GLOBAL_CONFIG.data_inline_pull_bytes:
                    # small-range fast path: payload rides the ack (one
                    # message RT, no frame-boundary syscalls — below
                    # ~100KB the pull is syscall-bound, not copy-bound);
                    # header + pickled body leave in ONE writev so the
                    # blocked puller wakes exactly once
                    data = os.pread(fd, n, offset)
                    if len(data) != n:
                        conn.send({"error": "short spool read"})
                        return True
                    protocol.send_msg_writev(
                        conn, {"size": size, "len": n, "data": data})
                    self._count_served(n, obj=offset == 0)
                    if span is not None:
                        tracing.emit_span(
                            "data.serve_stream", span, t0,
                            time.time() - t0, cat="data", bytes=n,
                            offset=offset, path="inline",
                            object_id=msg.get("object_id", ""))
                    return True
                conn.send({"size": size, "len": n})
                frame = max(64 * 1024, GLOBAL_CONFIG.data_stream_frame_bytes)
                if raw:
                    ok = self._stream_raw(conn, fd, offset, n, frame)
                else:
                    ok = self._stream_msgs(conn, fd, offset, n, frame)
            except (OSError, ValueError, EOFError):
                return False
        finally:
            try:
                os.close(fd)
            except OSError:
                pass
        if ok:
            self._count_served(n, obj=offset == 0)
            if span is not None:
                tracing.emit_span(
                    "data.serve_stream", span, t0, time.time() - t0,
                    cat="data", bytes=n, offset=offset,
                    path="raw" if raw else "relay",
                    object_id=msg.get("object_id", ""))
        return ok

    def _stream_raw(self, conn, in_fd: int, offset: int, n: int,
                    frame: int) -> bool:
        """Push ``n`` bytes as raw bulk frames on the socket fd:
        header write + ``os.sendfile`` from the spool file — the payload
        never enters userspace.  Falls back to pread+write when sendfile
        is unsupported for this fd pair."""
        out_fd = conn.fileno()
        use_sendfile = hasattr(os, "sendfile")
        sent = 0
        while sent < n:
            k = min(frame, n - sent)
            pos = offset + sent
            if not use_sendfile:
                # read BEFORE committing the frame header so a spool
                # read error can still surface as a recoverable ERR
                # frame instead of killing the pooled connection
                try:
                    data = os.pread(in_fd, k, pos)
                    if len(data) != k:
                        raise OSError(errno.EIO, "short spool read")
                except OSError as e:
                    err = str(e).encode("utf-8", "replace")
                    protocol.write_all(out_fd, wire.bulk_pack_header(
                        wire.BULK_ERR, len(err)) + err)
                    return True
                protocol.write_all(out_fd, wire.bulk_pack_header(
                    wire.BULK_DATA, k))
                protocol.write_all(out_fd, data)
                sent += k
                continue
            protocol.write_all(out_fd, wire.bulk_pack_header(
                wire.BULK_DATA, k))
            end = pos + k
            while pos < end:
                try:
                    m = os.sendfile(out_fd, in_fd, pos, end - pos)
                except OSError as e:
                    if e.errno in (errno.ENOSYS, errno.EINVAL) \
                            and pos == offset + sent:
                        # header already committed: deliver this frame
                        # by pread+write, then stop using sendfile
                        use_sendfile = False
                        data = os.pread(in_fd, end - pos, pos)
                        if len(data) != end - pos:
                            return False
                        protocol.write_all(out_fd, data)
                        m = len(data)
                    else:
                        raise
                if m <= 0:
                    raise OSError(errno.EIO, "sendfile stalled")
                pos += m
            sent += k
        protocol.write_all(out_fd, wire.bulk_pack_header(wire.BULK_END, 0))
        return True

    def _stream_msgs(self, conn, fd: int, offset: int, n: int,
                     frame: int) -> bool:
        """Proxy-safe streaming: each bulk frame rides one
        ``send_bytes`` message (the head's relay pump re-frames
        Connection messages; raw fd traffic would not survive it).
        Payloads are memoryview slices of the file's mmap — no pickle
        and no userspace staging copy."""
        if n == 0:
            return True
        import mmap
        mm = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
        try:
            mv = memoryview(mm)
            sent = 0
            while sent < n:
                k = min(frame, n - sent)
                conn.send_bytes(mv[offset + sent:offset + sent + k])
                sent += k
        finally:
            try:
                mv.release()
            except (NameError, BufferError):
                pass
            mm.close()
        return True

    def delete_local(self, object_id: str) -> None:
        """Producer-side spool eviction: unlink + fd-cache invalidate,
        same semantics as the remote ``delete_object`` op (in-flight
        streams keep their dup'd fd; later fetches miss)."""
        try:
            os.unlink(spool_path(self.spool_dir, object_id))
        except FileNotFoundError:
            pass
        self._fd_cache.invalidate(object_id)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self._fd_cache.close_all()
        # force-close live serving conns: their threads sit in recv();
        # shutdown() interrupts the read AND sends FIN so pooled peer
        # conns observe the death instead of waiting on a dead socket
        with self._stats_lock:
            conns = list(self._conns)
        for c in conns:
            protocol.shutdown_conn(c)


# ---------------------------------------------------------------- client
class _StreamError(Exception):
    """Protocol breakage mid-stream: the connection framing state is
    unknown and the conn must be discarded."""


class _StreamMiss(Exception):
    """Server-signaled miss at a clean frame boundary: the object is
    gone but the connection is still usable."""


class _LegacyPeer(Exception):
    """The holder answered ``fetch_stream``/hello with unknown-op — it
    runs the v0 protocol (e.g. restarted onto an older build after we
    cached v1 for its address)."""


def _negotiate_data_proto(conn) -> int:
    """Client half of the data-plane ``__proto_hello__``; a legacy
    server replies unknown-op error → version 0."""
    conn.send({"op": "__proto_hello__",
               "versions": list(range(wire.DATA_PROTO_MIN,
                                      wire.DATA_PROTO_MAX + 1))})
    # rtlint: blocks-ok(hello handshake on a fresh dial: every server
    # version replies to the first frame (legacy = unknown-op error),
    # so the reply or EOF arrives within the peer's serve latency; the
    # fetch leader's 120s coalesce cap bounds the caller)
    resp = conn.recv()
    if resp.get("error"):
        return 0
    return int(resp.get("proto", 0))


_PULL_CACHE_MIN = 1024 * 1024


class _PullBufferCache:
    """Already-faulted receive buffers reused across streamed pulls.

    Materializing the destination pages — NOT the transfer — is the
    dominant cost of a large pull once streaming is in place:
    ``bytearray(64MB)`` memsets every page (~50 ms here, longer than
    the 64 MB transfer itself), and a lazily-faulted anonymous mmap
    pays the same bill as page faults inside ``recv_into`` (worse on
    virtualized kernels where each fault is a host round trip).  A
    buffer whose pages are already resident streams at line rate with
    ~zero allocation cost, so this cache keeps recent pull buffers
    and hands them back out.

    Reuse safety: ``pull`` returns the SAME object the cache retains,
    so a buffer is reusable only while the cache holds the sole
    reference — checked with ``sys.getrefcount`` under the lock.  Any
    consumer still holding the buffer (or any memoryview/numpy view
    into it — views own a reference to the base) inflates the count
    and the buffer is skipped; the moment the consumer drops it, the
    next pull recycles the hot pages.  The scan-and-return runs
    entirely under the lock and the returned value is referenced by
    the caller's frame continuously from loop variable to return, so
    two racing pulls can never be handed the same buffer.

    Buffers below ``_PULL_CACHE_MIN`` are plain fresh bytearrays (a
    small memset is cheaper than pinning pages); the cache itself is
    LRU-bounded by ``data_pull_buffer_cache_mb`` — eviction just drops
    the cache's reference, so an evicted in-use buffer lives on with
    its consumer, it merely stops being reusable."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bufs: List = []  # LRU order, most-recent last; guarded by: _lock

    def alloc(self, n: int):
        """Writable bytes-like buffer of exactly ``n`` bytes."""
        if n < _PULL_CACHE_MIN:
            return bytearray(n)
        import sys
        with self._lock:
            for i in range(len(self._bufs) - 1, -1, -1):
                b = self._bufs[i]
                # 3 == our list + loop var + getrefcount's argument:
                # nobody outside this cache holds the buffer
                if len(b) >= n and sys.getrefcount(b) == 3:
                    del self._bufs[i]
                    self._bufs.append(b)
                    return memoryview(b)[:n] if len(b) > n else b
        import mmap
        # anonymous mmap over bytearray: no up-front zero-fill — first
        # use faults pages as recv_into streams through them
        buf = mmap.mmap(-1, n)
        cap = max(0, GLOBAL_CONFIG.data_pull_buffer_cache_mb) * 1024 * 1024
        if n <= cap:
            with self._lock:
                self._bufs.append(buf)
                total = sum(len(b) for b in self._bufs)
                while total > cap and len(self._bufs) > 1:
                    total -= len(self._bufs.pop(0))
        return buf

    def clear(self) -> None:
        with self._lock:
            self._bufs.clear()


def _pull_chunks(conn, object_id: str) -> bytearray:
    """v0 request-per-chunk pull (legacy holders; also the in-pool
    fallback when a cached-v1 address turns out to be v0)."""
    conn.send({"op": "fetch_object", "object_id": object_id})
    # rtlint: blocks-ok(request/reply on the v0 pull path: the holder
    # answers every op or EOFs; the fetch leader's 120s coalesce cap
    # (gcs._pull_remote_local) bounds the caller-visible wait)
    head = conn.recv()
    if "error" in head:
        raise FileNotFoundError(object_id)
    size = head["size"]
    chunk = GLOBAL_CONFIG.transfer_chunk_bytes
    buf = bytearray(size)
    off = 0
    while off < size:
        conn.send({"op": "fetch_chunk", "object_id": object_id,
                   "offset": off, "length": min(chunk, size - off)})
        # rtlint: blocks-ok(same request/reply contract and 120s
        # coalesce cap as the fetch_object head frame above)
        r = conn.recv()
        piece = r.get("data")
        if not piece:
            raise FileNotFoundError(object_id)
        buf[off:off + len(piece)] = piece
        off += len(piece)
    return buf


class _PoolConn:
    """One pooled data-plane connection (checked out by one thread at a
    time; the pool's lock never covers I/O on it)."""

    __slots__ = ("conn", "addr", "raw", "proto", "last_used")

    def __init__(self, conn, addr: str,
                 raw: bool, proto: int):  # rtlint: owns(conn)
        self.conn = conn
        self.addr = addr
        self.raw = raw          # direct fd (sendfile/recv_into legal)?
        self.proto = proto      # negotiated data-plane version
        self.last_used = time.monotonic()


def _default_dial(addr: str):
    """tcp:// dial with bulk tuning; (conn, raw=True)."""
    tcp = protocol.parse_tcp_addr(addr)
    if tcp is None:
        raise ConnectionError(f"not a tcp data address: {addr!r}")
    return protocol.connect_data(*tcp, timeout=3.0), True


class DataPlanePool:
    """Per-process pool of data-plane connections, keyed by peer
    address.  Repeated pulls and deletes to the same holder reuse one
    authenticated connection instead of paying dial+HMAC per object;
    a broken connection invalidates every pooled conn to that address
    (the peer likely died — mirrors ``RpcPool.invalidate``).  Idle
    connections beyond ``data_pool_max_conns`` close LRU-first."""

    def __init__(self, dial=None):
        self._dial = dial or _default_dial
        self._buffers = _PullBufferCache()
        self._lock = threading.Lock()
        self._idle: Dict[str, List[_PoolConn]] = {}  # guarded by: _lock
        self._open = 0                               # guarded by: _lock
        self._proto: Dict[str, int] = {}             # guarded by: _lock
        self._closed = False                         # guarded by: _lock

    # ------------------------------------------------------ conn lifecycle
    def _publish_open_locked(self) -> None:
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_data_pool_conns").set(self._open)

    def acquire(self, addr: str) -> _PoolConn:  # rtlint: returns(conn)
        with self._lock:
            lst = self._idle.get(addr)
            if lst:
                pc = lst.pop()
                if not lst:
                    del self._idle[addr]
                return pc
            known = self._proto.get(addr)
        conn, raw = self._dial(addr)
        try:
            proto = known if known is not None \
                else _negotiate_data_proto(conn)
        except (OSError, EOFError, ConnectionError):
            try:
                conn.close()
            except OSError:
                pass
            raise
        pc = _PoolConn(conn, addr, raw, proto)
        with self._lock:
            if known is None:
                self._proto[addr] = proto
            self._open += 1
            self._publish_open_locked()
        return pc

    def release(self, pc: _PoolConn) -> None:  # rtlint: owns(pc)
        """Return a healthy conn; evict LRU idles beyond the bound."""
        pc.last_used = time.monotonic()
        victims: List[_PoolConn] = []
        with self._lock:
            if self._closed:
                victims.append(pc)
                self._open -= 1
            else:
                self._idle.setdefault(pc.addr, []).append(pc)
                limit = max(1, GLOBAL_CONFIG.data_pool_max_conns)
                while sum(len(v) for v in self._idle.values()) > limit:
                    addr = min(self._idle,
                               key=lambda a: self._idle[a][0].last_used)
                    victims.append(self._idle[addr].pop(0))
                    if not self._idle[addr]:
                        del self._idle[addr]
                    self._open -= 1
            self._publish_open_locked()
        for v in victims:
            try:
                v.conn.close()
            except OSError:
                pass

    def discard(self, pc: _PoolConn) -> None:  # rtlint: owns(pc)
        """Drop a broken checked-out conn."""
        with self._lock:
            self._open -= 1
            self._publish_open_locked()
        try:
            pc.conn.close()
        except OSError:
            pass

    def invalidate(self, addr: str) -> None:
        """Close every idle conn to ``addr`` and forget its negotiated
        version — the reconnect primitive after a peer death."""
        with self._lock:
            victims = self._idle.pop(addr, [])
            self._proto.pop(addr, None)
            self._open -= len(victims)
            self._publish_open_locked()
        for v in victims:
            try:
                v.conn.close()
            except OSError:
                pass

    def set_proto(self, addr: str, proto: int) -> None:
        """Pre-seed a peer's data-plane version (the head learns it from
        node registration and skips the per-conn hello round trip)."""
        with self._lock:
            self._proto[addr] = int(proto)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"open": self._open,
                    "idle": sum(len(v) for v in self._idle.values())}

    def close_all(self) -> None:
        with self._lock:
            self._closed = True
            victims = [pc for lst in self._idle.values() for pc in lst]
            self._idle.clear()
            self._open -= len(victims)
            self._publish_open_locked()
        for v in victims:
            try:
                v.conn.close()
            except OSError:
                pass
        self._buffers.clear()

    # -------------------------------------------------------------- pulls
    def pull(self, addr: str, object_id: str,
             size: Optional[int] = None):
        """Fetch one object's wire bytes from the holder at ``addr``,
        as a writable bytes-like buffer (``bytearray``, or an
        anonymous ``mmap`` for large objects — see
        ``_alloc_pull_buffer``).

        v1 holders stream (range-striped in parallel above
        ``data_stripe_threshold_bytes`` when ``size`` is known); v0
        holders get the chunk protocol — still over a pooled conn, so
        even legacy pulls stop paying dial+HMAC per object."""
        t0 = time.monotonic()
        t0w = time.time()
        # the pull's child span is created BEFORE the transfer and
        # adopted for its duration, so the per-stream fetch_stream
        # requests carry ITS id — the holder's data.serve_stream spans
        # then nest under this data.pull node in the assembled tree
        from ray_tpu.util import tracing
        span = tracing.current_span()
        pull_ctx = tok = None
        if span is not None and span.sampled:
            pull_ctx = tracing.child_span(span, "data.pull")
            tok = tracing.adopt(pull_ctx)
        try:
            buf = self._pull(addr, object_id, size)
        finally:
            if tok is not None:
                tracing.restore(tok)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_data_pull_seconds").observe(
                time.monotonic() - t0, tags={"path": "direct"})
            mcat.get("rtpu_data_bytes_total").inc(len(buf),
                                                  tags={"dir": "in"})
        if pull_ctx is not None:
            tracing.emit_ctx_span(pull_ctx, "data.pull", t0w,
                                  time.monotonic() - t0, cat="data",
                                  bytes=len(buf), path="direct",
                                  object_id=object_id)
        return buf

    def _pull(self, addr: str, object_id: str,
              size: Optional[int]):
        cfg = GLOBAL_CONFIG
        pc = self.acquire(addr)
        try:
            if pc.proto >= 1:
                streams = int(cfg.data_stripe_streams)
                if size is not None and streams > 1 \
                        and size >= cfg.data_stripe_threshold_bytes:
                    buf = self._pull_striped(pc, addr, object_id, size,
                                             streams)
                else:
                    buf = self._pull_stream(pc, object_id)
            else:
                buf = _pull_chunks(pc.conn, object_id)
        except _LegacyPeer:
            # cached-v1 address now speaks v0 (peer restarted older):
            # renegotiate down and retry chunked on the same conn
            with self._lock:
                self._proto[addr] = 0
            pc.proto = 0
            try:
                buf = _pull_chunks(pc.conn, object_id)
            except FileNotFoundError:
                self.release(pc)
                raise
            except BaseException:
                self.discard(pc)
                self.invalidate(addr)
                raise
            self.release(pc)
            return buf
        except _StreamMiss:
            self.release(pc)
            raise FileNotFoundError(object_id) from None
        except FileNotFoundError:
            self.release(pc)  # clean miss: conn still good
            raise
        except BaseException:
            self.discard(pc)
            self.invalidate(addr)
            raise
        self.release(pc)
        return buf

    def _pull_stream(self, pc: _PoolConn, object_id: str):
        msg = {"op": "fetch_stream", "object_id": object_id,
               "offset": 0, "length": -1, "raw": pc.raw}
        if pc.proto >= wire.DATA_PROTO_TRACE:
            from ray_tpu.util import tracing
            tracing.attach_wire_trace(msg)
        pc.conn.send(msg)
        n, inline = self._read_stream_ack(pc, object_id, expect=None)
        if inline is not None:
            return bytearray(inline)
        buf = self._buffers.alloc(n)
        self._recv_stream(pc, memoryview(buf), n)
        return buf

    def _pull_striped(self, pc0: _PoolConn, addr: str, object_id: str,
                      size: int, streams: int):
        # each stripe should stay big enough to amortize its ack RTT
        k = min(streams, max(2, size // (8 * 1024 * 1024)))
        buf = self._buffers.alloc(size)
        mv = memoryview(buf)
        base = size // k
        bounds = [(i * base, base if i < k - 1 else size - (k - 1) * base)
                  for i in range(k)]
        errors: List[BaseException] = []
        # span context captured HERE: stripe threads are fresh threads,
        # the context variable does not follow them
        from ray_tpu.util import tracing
        ctx = tracing.current_span()

        def run(off: int, ln: int, pc: Optional[_PoolConn]) -> None:
            mine = pc is None
            try:
                if mine:
                    # settled on every path, but the discharge is
                    # mine-correlated (release/discard run iff this
                    # stripe acquired) — correlation beyond the analyzer
                    # rtlint: resource-leak-ok(mine-correlated settle)
                    pc = self.acquire(addr)
                self._stream_range(pc, object_id, mv[off:off + ln],
                                   off, ln, ctx=ctx)
            except BaseException as e:  # noqa: BLE001 - joined below
                errors.append(e)
                if mine and pc is not None:
                    self.discard(pc)
            else:
                if mine:
                    self.release(pc)

        threads = [threading.Thread(target=run, args=(off, ln, None),
                                    daemon=True, name="data-stripe-pull")
                   for off, ln in bounds[1:]]
        for t in threads:
            t.start()
        run(bounds[0][0], bounds[0][1], pc0)
        for t in threads:
            # rtlint: blocks-ok(stripe workers run _stream_range, whose
            # every blocking op is EOF/reset-terminated; a dead holder
            # errors all stripes and the joins return — the 120s fetch
            # coalesce cap bounds the caller)
            t.join()
        if errors:
            raise errors[0]
        return buf

    def _stream_range(self, pc: _PoolConn, object_id: str,
                      view: memoryview, offset: int, length: int,
                      ctx=None) -> None:
        msg = {"op": "fetch_stream", "object_id": object_id,
               "offset": offset, "length": length, "raw": pc.raw}
        if pc.proto >= wire.DATA_PROTO_TRACE:
            from ray_tpu.util import tracing
            tracing.attach_wire_trace(msg, ctx=ctx)
        pc.conn.send(msg)
        n, inline = self._read_stream_ack(pc, object_id, expect=length)
        if inline is not None:
            view[:n] = inline
            return
        self._recv_stream(pc, view[:n], n)

    def _read_stream_ack(self, pc: _PoolConn, object_id: str,
                         expect: Optional[int]):
        """(byte count, inline payload or None) from a fetch_stream ack
        — small ranges ride the ack itself, larger ones follow as bulk
        frames."""
        # rtlint: blocks-ok(ack for a just-sent fetch_stream: the
        # holder acks, errors, or EOFs; 120s fetch coalesce cap bounds
        # the caller-visible wait)
        head = pc.conn.recv()
        err = head.get("error")
        if err is not None:
            if "unknown op" in str(err):
                raise _LegacyPeer(err)
            raise FileNotFoundError(object_id)
        n = int(head["len"])
        if expect is not None and n != expect:
            # spool file changed size under a striped pull: sibling
            # stripes are already mid-flight against the old layout
            raise _StreamError(
                f"range ack {n} != requested {expect} for {object_id}")
        return n, head.get("data")

    def _recv_stream(self, pc: _PoolConn, view: memoryview,
                     n: int) -> None:
        if pc.raw:
            self._recv_stream_raw(pc.conn, view, n)
        else:
            self._recv_stream_msgs(pc.conn, view, n)

    @staticmethod
    def _recv_stream_raw(conn, view: memoryview, n: int) -> None:
        import socket as _socket
        hdr = bytearray(wire.BULK_HDR_LEN)
        hv = memoryview(hdr)
        got = 0
        # one socket wrapper for the whole stream: recv_exact_into's
        # MSG_WAITALL then drains each frame in a single syscall
        s = _socket.socket(fileno=conn.fileno())
        try:
            while True:
                # rtlint: blocks-ok(mid-stream read: the holder has
                # acked and is writing frames back-to-back; death mid-
                # stream resets the socket, 120s coalesce cap upstream)
                protocol.recv_exact_into(s, hv)
                kind, ln = wire.bulk_unpack_header(hdr)
                if kind == wire.BULK_DATA:
                    if got + ln > n:
                        raise _StreamError(
                            f"stream overrun ({got + ln} > {n})")
                    # rtlint: blocks-ok(same mid-stream contract as the
                    # header read above)
                    protocol.recv_exact_into(s, view[got:got + ln])
                    got += ln
                elif kind == wire.BULK_END:
                    break
                elif kind == wire.BULK_ERR:
                    eb = bytearray(ln)
                    # rtlint: blocks-ok(same mid-stream contract as the
                    # header read above)
                    protocol.recv_exact_into(s, memoryview(eb))
                    raise _StreamMiss(eb.decode("utf-8", "replace"))
                else:
                    raise _StreamError(f"bad bulk frame kind 0x{kind:02x}")
        finally:
            s.detach()  # fd ownership stays with the Connection
        if got != n:
            raise _StreamError(f"short stream ({got} of {n})")

    @staticmethod
    def _recv_stream_msgs(conn, view: memoryview, n: int) -> None:
        from multiprocessing.connection import BufferTooShort
        got = 0
        while got < n:
            try:
                # rtlint: blocks-ok(mid-stream read on the relay path —
                # same acked-stream contract as _recv_stream_raw, 120s
                # coalesce cap upstream)
                m = conn.recv_bytes_into(view, got)
            except BufferTooShort:
                raise _StreamError("stream overrun (relay)") from None
            if m == 0:
                raise _StreamMiss("stream aborted by holder")
            got += m

    # ------------------------------------------------------------ deletes
    def delete_batch(self, addr: str, object_ids,
                     max_redials: int = 2) -> None:
        """Best-effort spool delete of many objects over pooled
        connections.  A mid-batch hiccup drops only that object's delete
        and redials for the rest — but redials are BOUNDED: a peer that
        keeps dying (or a dead host whose dial times out) costs at most
        ``max_redials`` reconnect attempts for the whole batch, not one
        3s timeout per remaining object."""
        if not object_ids:
            return
        redials = 0
        pc: Optional[_PoolConn] = None
        try:
            for oid in object_ids:
                try:
                    if pc is None:
                        pc = self.acquire(addr)
                    pc.conn.send({"op": "delete_object", "object_id": oid})
                    pc.conn.recv()
                except (OSError, EOFError, ConnectionError):
                    if pc is None:
                        # the (re)dial itself failed: peer unreachable —
                        # drop the remaining deletes instead of paying a
                        # connect timeout per object
                        self.invalidate(addr)
                        return
                    self.discard(pc)
                    pc = None
                    redials += 1
                    if redials > max_redials:
                        self.invalidate(addr)
                        return  # repeatedly dying peer: give up on batch
        finally:
            if pc is not None:
                self.release(pc)
