"""Peer-to-peer object plane for multi-host clusters.

Reference: the ObjectManager's node↔node chunked transfer (PullManager /
PushManager, SURVEY.md §2.1) — data moves directly between the holder
host and the puller host; the head is only a *fallback relay* for hosts
that cannot reach each other (hub-spoke NAT topologies).

Mechanics here: each NodeAgent host keeps a **spool directory** of large
objects produced on that host (one file per object, written by the
producing worker — same host, plain file I/O) and runs a
``DataPlaneServer`` — a TCP listener (per-session HMAC auth, the same
handshake as every other socket) serving chunked reads of those files.
The GCS records ``loc="remote"`` + the holder node; consumers dial the
holder's advertised data address and stream chunks, falling back to the
head relay when the dial fails.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Optional, Tuple

from ray_tpu._private import protocol, rtlog
from ray_tpu._private.config import GLOBAL_CONFIG

logger = rtlog.get("data-plane")


def spool_path(spool_dir: str, object_id: str) -> Path:
    return Path(spool_dir) / f"obj_{object_id}"


def spool_capacity_bytes() -> int:
    mb = int(os.environ.get("RTPU_SPOOL_CAPACITY_MB", 0) or 0)
    if mb <= 0:
        mb = GLOBAL_CONFIG.object_store_memory_mb
    return mb * 1024 * 1024


def write_spool(spool_dir: str, object_id: str, wire) -> int:
    """Atomic write of an object's wire bytes into the host spool.

    Admission-checked against the spool capacity (default: the object
    store capacity — the replaced head-upload path enforced the head
    store's bound; an unbounded spool on a tmpfs-backed /tmp would OOM
    the host with no backpressure).  The scan is O(spooled files);
    spooled objects are large, so counts stay small.

    Admission (scan + reservation) runs under a per-spool flock so N
    concurrent producers can't each pass the check and collectively
    overshoot the capacity; the reservation is an ftruncate of the .tmp
    file to full size, which later scanners count, so the bulk data copy
    itself happens outside the lock."""
    import fcntl

    size = len(wire)
    cap = spool_capacity_bytes()
    path = spool_path(spool_dir, object_id)
    tmp = path.with_suffix(".tmp")
    with open(Path(spool_dir) / ".admission.lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        used = 0
        import time as _time
        now = _time.time()
        try:
            with os.scandir(spool_dir) as it:
                for e in it:
                    if e.name == ".admission.lock":
                        continue
                    try:
                        st = e.stat()
                        if e.name.endswith(".tmp") and \
                                now - st.st_mtime > 300:
                            # orphaned reservation: a writer SIGKILLed
                            # mid-write (e.g. by the per-node OOM killer)
                            # never runs its cleanup — sweep it here or it
                            # counts against capacity forever
                            os.unlink(e.path)
                            continue
                        used += st.st_size
                    except OSError:
                        pass
        except OSError:
            pass
        if used + size > cap:
            from ray_tpu.exceptions import ObjectStoreFullError
            raise ObjectStoreFullError(
                f"host spool full: {used + size} > {cap} bytes "
                f"(RTPU_SPOOL_CAPACITY_MB to raise)")
        f = open(tmp, "wb")
        try:
            f.truncate(size)  # reserve while still under the lock
        except OSError:
            pass
    try:
        f.write(wire)
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        try:
            os.unlink(tmp)  # failed write must not hold its reservation
        except OSError:
            pass
        raise
    return size


class DataPlaneServer:
    """Serves chunked reads of one host's object spool.

    Ops (framed-pickle messages, same wire as the control plane):
      fetch_object: {object_id} → {size} | {error}
      fetch_chunk:  {object_id, offset, length} → {data}
      delete_object:{object_id} → {}           (refcount hit zero)
      stats:        {} → {bytes_served, objects_served}
    """

    def __init__(self, spool_dir: str, host: str = "0.0.0.0",
                 advertise_host: Optional[str] = None):
        self.spool_dir = spool_dir
        Path(spool_dir).mkdir(parents=True, exist_ok=True)
        self._listener = protocol.make_tcp_listener(host, 0)
        self.port = self._listener.address[1]
        self.advertise_addr = f"tcp://{advertise_host or host}:{self.port}"
        self.bytes_served = 0
        self.objects_served = 0
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, name="data-plane",
                         daemon=True).start()

    def _accept_loop(self) -> None:
        protocol.serve_accept_loop(self._listener, self._stop.is_set,
                                   self._serve, "data-plane-serve")

    def _serve(self, conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                op = msg.get("op")
                oid = msg.get("object_id", "")
                path = spool_path(self.spool_dir, oid)
                try:
                    if op == "fetch_object":
                        self.objects_served += 1
                        conn.send({"size": path.stat().st_size})
                    elif op == "fetch_chunk":
                        with open(path, "rb") as f:
                            data = os.pread(f.fileno(), msg["length"],
                                            msg["offset"])
                        self.bytes_served += len(data)
                        conn.send({"data": data})
                    elif op == "delete_object":
                        try:
                            os.unlink(path)
                        except FileNotFoundError:
                            pass
                        conn.send({})
                    elif op == "stats":
                        conn.send({"bytes_served": self.bytes_served,
                                   "objects_served": self.objects_served})
                    else:
                        conn.send({"error": f"unknown op {op!r}"})
                except FileNotFoundError:
                    conn.send({"error": "not found"})
                except OSError as e:
                    conn.send({"error": str(e)})
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


def pull_from_peer(open_conn, addr: str, object_id: str) -> bytearray:
    """Stream one object from a holder host's data plane.

    ``open_conn(addr)`` supplies the connection — Worker.open_conn, which
    dials tcp addresses directly with a bounded handshake and falls back
    to the head's proxy relay for unreachable peers (hub-spoke), giving
    exactly the reference PullManager's direct-else-relay behavior."""
    conn = open_conn(addr)
    try:
        conn.send({"op": "fetch_object", "object_id": object_id})
        head = conn.recv()
        if "error" in head:
            raise FileNotFoundError(object_id)
        size = head["size"]
        chunk = GLOBAL_CONFIG.transfer_chunk_bytes
        buf = bytearray(size)
        off = 0
        while off < size:
            conn.send({"op": "fetch_chunk", "object_id": object_id,
                       "offset": off, "length": min(chunk, size - off)})
            r = conn.recv()
            piece = r.get("data")
            if not piece:
                raise FileNotFoundError(object_id)
            buf[off:off + len(piece)] = piece
            off += len(piece)
        return buf
    finally:
        try:
            conn.close()
        except OSError:
            pass


def delete_on_peer(addr: str, object_id: str) -> None:
    """Best-effort spool delete on the holder (refcount reached zero)."""
    delete_batch_on_peer(addr, [object_id])


def delete_batch_on_peer(addr: str, object_ids) -> None:
    """Best-effort spool delete of many objects over ONE connection —
    bulk releases (driver exit, 64-wide release batches) must not pay a
    TCP connect per object.  A mid-batch hiccup drops only that object's
    delete and reconnects for the rest (narrower blast radius than
    aborting the batch); an unreachable peer gives up immediately."""
    tcp = protocol.parse_tcp_addr(addr)
    if tcp is None or not object_ids:
        return
    conn = None
    try:
        for oid in object_ids:
            try:
                if conn is None:
                    conn = protocol.connect_tcp(*tcp, timeout=3.0)
                conn.send({"op": "delete_object", "object_id": oid})
                conn.recv()
            except (OSError, EOFError, ConnectionError):
                if conn is None:
                    return  # connect itself failed: peer unreachable
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None  # reconnect for the remaining objects
    finally:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
