"""Opt-in runtime resource-leak sanitizer (DESIGN.md §4f).

``RAY_TPU_RESOURCE_SANITIZER=1`` is the dynamic half of rtlint's
static resource pass (``tools/rtlint/resources.py``), the same pairing
as ``RAY_TPU_LOCK_WATCHDOG=1`` / the lock-order pass: the static pass
proves discharge-on-every-path over the AST; this module measures NET
leaked resources in a live process and names the acquisition stack of
every survivor.

Mechanism: :func:`install` patches the process-wide acquisition points

- ``socket.socket`` (tracked subclass — ``accept``/``dup``/
  ``socketpair``/``create_connection`` all construct through the
  module global, so they are covered too),
- ``mmap.mmap`` (tracked subclass),
- ``os.open`` / ``os.dup`` (raw-fd registry; ``os.close`` discharges),
- ``threading.Thread.start`` (non-daemon threads only — daemon threads
  are strandable by declared policy, enforced by rtlint's thread pass),
- ``multiprocessing.connection.Connection.__init__`` (every protocol
  dial and every accepted peer lands here),

recording a ``traceback.format_stack()`` per acquisition in a global
:class:`ResourceRegistry`.  Nothing hooks ``close()``: each entry
holds a weakref plus a *liveness predicate* (``sock.fileno() == -1``,
``f.closed``, ``conn.closed``, ``not thread.is_alive()``, fstat on raw
fds) evaluated at assert time, so any discharge path — ``close``,
``detach``, ``with``, GC finalizer — counts without instrumenting it.

:func:`assert_clean` (wired into ``GcsServer.shutdown``, the worker
main-loop exit, and the leak-hammer in
``tests/test_resource_sanitizer.py``) garbage-collects, polls until a
grace deadline for in-flight teardown on daemon threads, and raises
:class:`ResourceLeakError` listing every survivor with the stack that
acquired it.

Known imprecision (documented so nobody trusts it for what it cannot
do): a raw fd closed by a wrapper OTHER than ``os.close`` (e.g.
``os.fdopen(fd).close()``) stays registered until the fstat probe sees
EBADF — and if the fd number was reused by an untracked open, the
probe reports the REUSED resource as leaked.  Baseline resources
acquired before :func:`install` are never tracked.
"""

from __future__ import annotations

import gc
import os
import threading
import time
import traceback
import weakref
from typing import Callable, Dict, List, Optional, Tuple

_ENV = "RAY_TPU_RESOURCE_SANITIZER"


def sanitizer_enabled() -> bool:
    return os.environ.get(_ENV) == "1"


class ResourceLeakError(RuntimeError):
    """Net resources survived a clean shutdown; message carries the
    acquisition stack of each survivor."""


class _Entry:
    __slots__ = ("kind", "desc", "stack", "created", "ref", "probe")

    def __init__(self, kind: str, desc: str, stack: List[str],
                 ref, probe: Optional[Callable[[], bool]]):
        self.kind = kind
        self.desc = desc
        self.stack = stack
        self.created = time.time()
        self.ref = ref          # weakref/strong ref/raw fd int, or None
        self.probe = probe      # () -> still-leaked?


class ResourceRegistry:
    """Stack-recording registry of live leakable resources."""

    def __init__(self, capture_stacks: bool = True):
        self._mu = threading.Lock()
        self._entries: Dict[int, _Entry] = {}   # key -> entry
        self._next_key = 0
        self._capture = capture_stacks
        # reentrancy guard: capturing a stack may itself acquire
        # resources (linecache file reads) — never re-enter
        self._tls = threading.local()
        self.acquired: Dict[str, int] = {}      # kind -> total ever seen

    # ------------------------------------------------------------ recording
    def _stack(self) -> List[str]:
        if not self._capture:
            return []
        stack = traceback.format_stack()
        while stack and __file__ in stack[-1]:
            stack.pop()
        return stack

    def register(self, kind: str, desc: str,
                 probe: Callable[[], bool]) -> Optional[int]:
        if getattr(self._tls, "busy", False):
            return None
        self._tls.busy = True
        try:
            stack = self._stack()
            with self._mu:
                key = self._next_key
                self._next_key += 1
                self._entries[key] = _Entry(kind, desc, stack, None, probe)
                self.acquired[kind] = self.acquired.get(kind, 0) + 1
            return key
        finally:
            self._tls.busy = False

    def register_obj(self, kind: str, obj, desc: str,
                     probe: Callable[[object], bool]) -> Optional[int]:
        """Track ``obj`` via weakref: a collected object is discharged
        (CPython refcounting runs its finalizer, which closes it);
        a live one is probed.  Objects that refuse weakrefs are held
        strongly — the probe alone decides."""
        try:
            ref = weakref.ref(obj)
        except TypeError:
            ref = lambda o=obj: o  # noqa: E731 - strong-ref fallback

        def _probe() -> bool:
            o = ref()
            return o is not None and probe(o)
        return self.register(kind, desc, _probe)

    def unregister(self, key: Optional[int]) -> None:
        if key is None:
            return
        with self._mu:
            self._entries.pop(key, None)

    # ------------------------------------------------------------ reporting
    def live(self) -> List[_Entry]:
        """Entries whose probe still reports the resource as leaked
        (probe errors count as leaked: an undiagnosable resource is a
        finding, not a pass)."""
        with self._mu:
            entries = list(self._entries.items())
        out = []
        dead = []
        for key, e in entries:
            try:
                leaked = e.probe()
            except Exception:  # noqa: BLE001 - treat as leaked
                leaked = True
            if leaked:
                out.append(e)
            else:
                dead.append(key)
        if dead:
            with self._mu:
                for k in dead:
                    self._entries.pop(k, None)
        return out

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.live():
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    def report(self, entries: Optional[List[_Entry]] = None,
               limit: int = 20) -> str:
        entries = self.live() if entries is None else entries
        lines = [f"{len(entries)} leaked resource(s):"]
        for e in entries[:limit]:
            lines.append(f"--- {e.kind} {e.desc} (acquired "
                         f"{time.time() - e.created:.1f}s ago) ---")
            lines.append("".join(e.stack) or "  <no stack recorded>")
        if len(entries) > limit:
            lines.append(f"... and {len(entries) - limit} more")
        return "\n".join(lines)

    def assert_clean(self, tag: str = "", grace_s: float = 2.0) -> None:
        """Raise :class:`ResourceLeakError` when net resources remain
        after ``grace_s`` (daemon serve threads may still be mid-
        teardown when shutdown returns — poll, don't guess)."""
        deadline = time.monotonic() + grace_s
        while True:
            gc.collect()
            entries = self.live()
            if not entries:
                return
            if time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        raise ResourceLeakError(
            f"resource sanitizer [{tag}]: {self.report(entries)}")


# ---------------------------------------------------------------- patching
_REGISTRY: Optional[ResourceRegistry] = None
_ORIG: Dict[str, object] = {}


def get_registry() -> Optional[ResourceRegistry]:
    return _REGISTRY


def _fd_probe(fd: int) -> Callable[[], bool]:
    def probe() -> bool:
        try:
            os.fstat(fd)
        except OSError:
            return False  # EBADF: closed by some other path
        return True
    return probe


def install() -> ResourceRegistry:
    """Patch the acquisition points; idempotent.  Process-global, so
    only the sanitizer entry points (``maybe_install``) and tests call
    this directly."""
    global _REGISTRY
    if _REGISTRY is not None:
        return _REGISTRY
    reg = ResourceRegistry()

    import mmap as mmap_mod
    import multiprocessing.connection as mpc
    import socket as socket_mod

    _ORIG["socket"] = socket_mod.socket
    _ORIG["mmap"] = mmap_mod.mmap
    _ORIG["os.open"] = os.open
    _ORIG["os.dup"] = os.dup
    _ORIG["os.close"] = os.close
    _ORIG["thread.start"] = threading.Thread.start
    _ORIG["conn.init"] = mpc.Connection.__init__

    class _TrackedSocket(socket_mod.socket):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            reg.register_obj("socket", self,
                             f"fd={self.fileno()}",
                             lambda s: s.fileno() != -1)

    class _TrackedMmap(mmap_mod.mmap):
        def __new__(cls, *a, **kw):
            m = super().__new__(cls, *a, **kw)
            reg.register_obj("mmap", m, f"len={len(m)}",
                             lambda o: not o.closed)
            return m

    fd_keys: Dict[int, int] = {}
    fd_mu = threading.Lock()

    def _track_fd(fd: int, desc: str) -> int:
        key = reg.register("fd", desc, _fd_probe(fd))
        if key is not None:
            with fd_mu:
                old = fd_keys.pop(fd, None)
                if old is not None:
                    # the number was reused without an os.close we saw
                    # (fdopen-style discharge): the old entry is dead
                    reg.unregister(old)
                fd_keys[fd] = key
        return fd

    orig_open, orig_dup, orig_close = os.open, os.dup, os.close

    def _os_open(path, flags, mode=0o777, *, dir_fd=None):
        return _track_fd(orig_open(path, flags, mode, dir_fd=dir_fd),
                         f"os.open({path!r})")

    def _os_dup(fd):
        return _track_fd(orig_dup(fd), f"os.dup({fd})")

    def _os_close(fd):
        # pop BEFORE the kernel close: the moment orig_close returns,
        # the fd number is free for a concurrent open to reuse —
        # popping after would untrack that new resource (false-negative
        # leak).  A failed close (EBADF) still drops the entry: the
        # registration was stale.
        with fd_mu:
            key = fd_keys.pop(fd, None)
        try:
            orig_close(fd)
        finally:
            reg.unregister(key)

    orig_start = threading.Thread.start

    def _start(self):
        if not self.daemon:
            # rtlint's thread pass forces the daemon= decision to be
            # explicit; the sanitizer holds non-daemon threads to the
            # join/transfer contract the static pass checks
            reg.register_obj("thread", self, self.name or "<unnamed>",
                             lambda t: t.is_alive())
        return orig_start(self)

    orig_conn_init = mpc.Connection.__init__

    def _conn_init(self, *a, **kw):
        orig_conn_init(self, *a, **kw)
        reg.register_obj("conn", self, repr(self),
                         lambda c: not c.closed)

    socket_mod.socket = _TrackedSocket
    mmap_mod.mmap = _TrackedMmap
    os.open = _os_open
    os.dup = _os_dup
    os.close = _os_close
    threading.Thread.start = _start
    mpc.Connection.__init__ = _conn_init
    _REGISTRY = reg
    return reg


def uninstall() -> None:
    """Restore the original acquisition points (tests only)."""
    global _REGISTRY
    if _REGISTRY is None:
        return
    import mmap as mmap_mod
    import multiprocessing.connection as mpc
    import socket as socket_mod
    socket_mod.socket = _ORIG.pop("socket")
    mmap_mod.mmap = _ORIG.pop("mmap")
    os.open = _ORIG.pop("os.open")
    os.dup = _ORIG.pop("os.dup")
    os.close = _ORIG.pop("os.close")
    threading.Thread.start = _ORIG.pop("thread.start")
    mpc.Connection.__init__ = _ORIG.pop("conn.init")
    _REGISTRY = None


def maybe_install() -> Optional[ResourceRegistry]:
    """Entry-point hook: install iff ``RAY_TPU_RESOURCE_SANITIZER=1``.
    Called from ``GcsServer.__init__`` and the spawned-worker main —
    the env var rides ``Popen`` inheritance to every worker."""
    if sanitizer_enabled():
        return install()
    return None


def assert_clean_at_shutdown(tag: str) -> None:
    """Shutdown hook: no-op unless the sanitizer is installed."""
    if _REGISTRY is not None and sanitizer_enabled():
        _REGISTRY.assert_clean(tag=tag)
