"""Session directory management.

Mirrors the reference's ``/tmp/ray/session_*`` layout (reference:
``python/ray/_private/node.py``; SURVEY.md §2.3): every ``init()`` creates a
timestamped session dir holding logs, unix sockets, the object-store spill
area, and a ``session.json`` descriptor that late-joining processes read to
find the control plane.
"""

from __future__ import annotations

import json
import os
import socket
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Optional

from ray_tpu._private.config import GLOBAL_CONFIG


class Session:
    def __init__(self, root: Optional[str] = None, name: Optional[str] = None):
        root_dir = Path(root or GLOBAL_CONFIG.session_dir_root)
        if name is None:
            stamp = time.strftime("%Y-%m-%d_%H-%M-%S")
            name = f"session_{stamp}_{os.getpid()}_{uuid.uuid4().hex[:6]}"
        self.name = name
        self.path = root_dir / name
        (self.path / "logs").mkdir(parents=True, exist_ok=True)
        (self.path / "sockets").mkdir(parents=True, exist_ok=True)
        (self.path / "spill").mkdir(parents=True, exist_ok=True)
        latest = root_dir / "session_latest"
        try:
            if latest.is_symlink() or latest.exists():
                latest.unlink()
            latest.symlink_to(self.path)
        except OSError:
            pass  # concurrent sessions racing on the symlink is fine

    @property
    def log_dir(self) -> Path:
        return self.path / "logs"

    @property
    def socket_dir(self) -> Path:
        return self.path / "sockets"

    @property
    def spill_dir(self) -> Path:
        spill = GLOBAL_CONFIG.object_spill_dir
        return Path(spill) if spill else self.path / "spill"

    def socket_path(self, name: str) -> str:
        # Unix socket paths are limited to ~107 bytes; keep names short.
        return str(self.socket_dir / name)

    def auth_key(self) -> bytes:
        """Per-session control-plane secret (HMAC key for every socket).

        Created once by the first accessor (the head), mode 0600; every
        process of the session reads it from the session dir.  Remote
        clients must receive it out-of-band (RTPU_AUTH_KEY) — the
        multiprocessing handshake then provides real authentication
        instead of a publicly-known constant."""
        p = self.path / "auth.key"
        try:
            return bytes.fromhex(p.read_text().strip())
        except FileNotFoundError:
            pass
        key = os.urandom(32)
        # write-then-link so a concurrent reader never sees a partial file
        # (which would become its HMAC key and fail every handshake).
        # O_TRUNC (not O_EXCL): a stale tmp from a killed pid is overwritten
        # rather than crashing startup forever.
        tmp = p.with_name(f".auth.key.{os.getpid()}")
        try:
            fd = os.open(str(tmp), os.O_CREAT | os.O_TRUNC | os.O_WRONLY,
                         0o600)
            with os.fdopen(fd, "w") as f:
                f.write(key.hex())
            os.link(str(tmp), str(p))  # fails if a racer published first
            return key
        except FileExistsError:
            return bytes.fromhex(p.read_text().strip())
        finally:
            try:
                os.unlink(str(tmp))
            except FileNotFoundError:
                pass

    def slab_path(self) -> str:
        """Path of the session's native slab store segment (C++ small-object
        data plane; ray_tpu/native/src/slab_store.cc). Derived from the
        session name so late-joining workers find it without a descriptor."""
        import hashlib
        tag = hashlib.md5(self.name.encode()).hexdigest()[:12]
        return f"/dev/shm/rtpu_slab_{tag}"

    def write_descriptor(self, info: Dict[str, Any]) -> None:
        desc = dict(info)
        desc["session_name"] = self.name
        desc["hostname"] = socket.gethostname()
        desc["pid"] = os.getpid()
        (self.path / "session.json").write_text(json.dumps(desc, indent=2))

    def read_descriptor(self) -> Dict[str, Any]:
        return json.loads((self.path / "session.json").read_text())

    @classmethod
    def latest(cls, root: Optional[str] = None) -> "Session":
        root_dir = Path(root or GLOBAL_CONFIG.session_dir_root)
        target = (root_dir / "session_latest").resolve()
        if not target.exists():
            raise FileNotFoundError("no ray_tpu session found")
        return cls(root=str(root_dir), name=target.name)
