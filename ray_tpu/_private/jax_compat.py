"""Version shims for the jax APIs ray_tpu's ops layer depends on.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its replication-check kwarg was renamed
``check_rep`` → ``check_vma``) across the jax versions this repo must
run on.  Every in-tree caller goes through :func:`shard_map` so the
resolution and the kwarg translation live in exactly one place; a jax
build with NEITHER spelling gets a precise error (tests skip on
:func:`shard_map_available`, not on a generic AttributeError).
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - ancient/exotic builds
        _shard_map = None

_PARAMS = (frozenset(inspect.signature(_shard_map).parameters)
           if _shard_map is not None else frozenset())


def shard_map_available() -> bool:
    return _shard_map is not None


def partial_shard_map_available() -> bool:
    """True when shard_map can leave a strict subset of mesh axes in
    GSPMD-automatic mode (native ``axis_names=``).  The experimental
    spelling expresses this via ``auto=``, but on the jaxlib builds
    that still ship it the partial-manual region lowers through a
    ``PartitionId`` op that the SPMD partitioner rejects — so only the
    native spelling counts as supported."""
    return _shard_map is not None and "axis_names" in _PARAMS


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """``jax.shard_map`` with the kwarg spelling this build expects."""
    if _shard_map is None:
        raise NotImplementedError(
            "this jax build has neither jax.shard_map nor "
            "jax.experimental.shard_map — ring/ulysses attention and "
            "xla collective groups need one of them")
    kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        if "check_vma" in _PARAMS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _PARAMS:
            kw["check_rep"] = check_vma
        # neither: the build predates the check knob — drop it
    if axis_names is not None:
        if "axis_names" in _PARAMS:
            kw["axis_names"] = axis_names
        elif frozenset(axis_names) != frozenset(mesh.axis_names):
            # the experimental spelling writes this as auto=<complement>,
            # but on the builds that still ship it the partial-manual
            # region lowers through PartitionId and the SPMD partitioner
            # rejects it — fail precisely here instead of deep in XLA
            # (callers gate on partial_shard_map_available())
            raise NotImplementedError(
                "this jax build's shard_map cannot run a partial "
                f"axis_names={set(axis_names)!r} over mesh axes "
                f"{set(mesh.axis_names)!r} (no native jax.shard_map; "
                "the experimental auto= lowering is rejected by SPMD "
                "partitioning)")
    return _shard_map(f, **kw)
