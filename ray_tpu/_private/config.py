"""Runtime flag registry for ray_tpu.

TPU-native analog of the reference's ``RayConfig`` (reference:
``src/ray/common/ray_config_def.h`` — one macro per flag, env-overridable via
``RAY_<NAME>``; see SURVEY.md §5.6).  Here every flag is declared once in
``_FLAG_DEFS`` and is overridable via the environment variable
``RTPU_<NAME>`` (uppercased).  ``ray_tpu.init(_system_config={...})`` merges a
dict on top, mirroring the reference's ``_system_config`` JSON passthrough.

Design difference from the reference: there is no separate native flag
registry — the C++ components read their few knobs through their ctypes init
call, so this single Python registry is the source of truth for both worlds.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_ENV_PREFIX = "RTPU_"


@dataclass(frozen=True)
class _FlagDef:
    name: str
    default: Any
    type: Callable[[str], Any]
    doc: str


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _flag(name: str, default: Any, doc: str) -> _FlagDef:
    if isinstance(default, bool):
        typ: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        typ = int
    elif isinstance(default, float):
        typ = float
    else:
        typ = str
    return _FlagDef(name, default, typ, doc)


# One entry per runtime knob.  Keep alphabetized within section.
_FLAG_DEFS = [
    # --- session / logging ---------------------------------------------------
    _flag("log_level", "INFO", "Root log level for ray_tpu processes."),
    _flag("log_to_driver", True, "Ship worker stdout/stderr lines to the driver."),
    # NOT /tmp/ray_tpu: a directory named exactly like the package would
    # shadow the import for any process with cwd=/tmp.
    _flag("session_dir_root", "/tmp/rtpu_sessions", "Root for session_* directories."),
    # --- object store --------------------------------------------------------
    _flag("object_store_memory_mb", 2048, "Shared-memory object store capacity."),
    _flag("inline_object_max_bytes", 100 * 1024,
          "Objects <= this are inlined in the control plane (in-memory store) "
          "instead of shared memory (reference: core worker memory store)."),
    _flag("object_spill_dir", "", "Directory for spilled objects ('' = <session>/spill)."),
    _flag("object_store_eviction", True,
          "LRU-evict sealed unreferenced objects to disk when full."),
    _flag("use_native_store", True, "Use the C++ shm store if the extension builds."),
    _flag("slab_memory_mb", 512, "Capacity of the native slab store (small-object plane)."),
    _flag("slab_object_max_bytes", 1024 * 1024,
          "Objects <= this go through the C++ slab store; larger ones get "
          "their own tmpfs segment (zero-copy mmap reads)."),
    _flag("memory_usage_threshold", 0.95,
          "Node memory fraction above which the memory monitor kills the "
          "newest running task's worker (reference: MemoryMonitor OOM "
          "killing; 1.0 disables)."),
    _flag("memory_monitor_interval_s", 1.0,
          "How often the memory monitor samples node usage."),
    _flag("gcs_snapshot", True,
          "Persist durable GCS tables (KV, functions, actors, placement "
          "groups) to <session>/gcs_state so a restarted head recovers "
          "them (reference: GCS fault tolerance via Redis persistence)."),
    _flag("gcs_reconnect_timeout_s", 30.0,
          "How long workers and drivers retry reconnecting to a dead GCS "
          "socket before giving up (reference: raylets reconnecting to a "
          "restarted GCS)."),
    _flag("gcs_reconnect_deadline_s", 5.0,
          "Per-dial bounded jittered backoff when the GCS endpoint is "
          "DEAD (connection refused / socket file missing) — a head "
          "failover window surfaces as latency instead of "
          "ConnectionRefusedError.  0 fails fast (seed behavior)."),
    _flag("gcs_wal", True,
          "Write-ahead log of durable ledger mutations (fsynced in "
          "drain batches) under <session>/gcs_state, replayed on top "
          "of the newest snapshot at head restart and streamed to "
          "attached warm standbys (DESIGN.md §4l).  Requires "
          "gcs_snapshot."),
    _flag("gcs_wal_fsync", True,
          "fsync each WAL drain batch (group commit).  Disabling "
          "trades the host-crash guarantee for lower write latency; "
          "process-crash durability is unaffected."),
    _flag("gcs_repl_heartbeat_s", 0.2,
          "Replication heartbeat / epoch-fence poll period on the "
          "primary's replication drain thread."),
    _flag("gcs_repl_tsdb_interval_s", 2.0,
          "How often the primary ships head-TSDB ring deltas to "
          "attached standbys (history handoff; telemetry-grade, "
          "best-effort)."),
    _flag("gcs_standby_timeout_s", 1.0,
          "A standby promotes after this long without any replication "
          "frame (heartbeats arrive every gcs_repl_heartbeat_s), or "
          "immediately on stream EOF with the endpoint verified dead."),
    _flag("gcs_restore_grace_s", 8.0,
          "After a restored-head start, how long restored actors may wait "
          "for their surviving worker process to reattach before the "
          "normal restart path (max_restarts) takes over."),
    _flag("transfer_chunk_bytes", 4 * 1024 * 1024,
          "Cross-host object transfers stream in chunks of this size "
          "(reference: ObjectManager chunked transfer) instead of one "
          "monolithic control-plane message."),
    _flag("transfer_max_inflight", 2,
          "Concurrent chunked pulls per process; further pulls queue "
          "(reference: PullManager bandwidth admission)."),
    _flag("data_stream_frame_bytes", 8 * 1024 * 1024,
          "Payload bytes per bulk frame on a streamed peer pull "
          "(fetch_stream).  Frames only bound how often a mid-stream "
          "error can surface — there is no per-frame round trip."),
    _flag("data_inline_pull_bytes", 128 * 1024,
          "Streamed pulls at or below this ride the fetch_stream ack "
          "itself (one message round trip, no bulk frames) — below "
          "~100KB the pull is syscall-bound, not copy-bound, so one "
          "pickled copy beats four frame-boundary syscalls."),
    _flag("data_stripe_threshold_bytes", 32 * 1024 * 1024,
          "Peer pulls of objects >= this open N parallel range-striped "
          "streams over pooled connections (data_stripe_streams); "
          "smaller objects ride one stream."),
    _flag("data_stripe_streams", 4,
          "Parallel range streams per striped peer pull (>=2; 1 "
          "disables striping)."),
    _flag("data_pool_max_conns", 16,
          "Per-process data-plane connection pool bound: idle "
          "connections beyond this are closed LRU-first (in-use "
          "connections are never reclaimed)."),
    _flag("data_pull_buffer_cache_mb", 256,
          "Per-process cap on cached streamed-pull receive buffers "
          "(already-faulted pages reused across pulls — allocation + "
          "page-fault cost otherwise rivals the transfer itself for "
          "large objects).  0 disables caching."),
    # --- scheduler / workers -------------------------------------------------
    _flag("num_workers_per_node", 0, "Size of worker pool (0 = num_cpus)."),
    _flag("prestart_workers", 0,
          "Plain workers forked eagerly at head start (warm pool: Serve "
          "scale-ups and first tasks skip the worker boot; reference: "
          "prestart_worker_first_driver)."),
    _flag("worker_register_timeout_s", 30.0, "Timeout for a spawned worker to register."),
    _flag("actor_connect_timeout_s", 60.0,
          "Caller-side wait for a pending actor to come ALIVE before its "
          "first method call fails (a saturated host spawning a large "
          "fleet can need more; RTPU_ACTOR_CONNECT_TIMEOUT_S)."),
    _flag("worker_lease_cache", True, "Reuse leased idle workers for same-shape tasks."),
    _flag("worker_pipeline_depth", 4,
          "Same-shape tasks queued on a busy worker's lease (scheduler-"
          "side; dispatched back-to-back on task completion without a "
          "pump scan).  0 disables (reference: lease reuse)."),
    _flag("scheduler_spread_threshold", 0.5,
          "Hybrid policy: prefer local until local load exceeds this fraction."),
    # --- raylet (per-node local scheduler, DESIGN.md §4i) --------------------
    _flag("raylet_enabled", True,
          "Promote each NodeAgent into a raylet: a per-node local "
          "scheduler that claims worker leases from the GCS in bulk, "
          "dispatches intra-node tasks without a head round-trip, and "
          "reconciles refcounts/results asynchronously (reference: "
          "src/ray/raylet NodeManager + LocalTaskManager).  Requires the "
          "head to speak wire proto >= PROTO_RAYLET; older heads fall "
          "back to the legacy direct-GCS worker pool automatically."),
    _flag("raylet_lease_backlog", 16,
          "Queued lease depth per raylet node: plain-CPU specs granted "
          "beyond the node's resource fit, queued locally and started "
          "by same-shape lease handoff or on an idle worker "
          "(node-scoped generalization of worker_pipeline_depth; "
          "concurrency stays bounded by the worker pool).  0 disables "
          "oversubscribed grants."),
    _flag("raylet_reconcile_interval_s", 0.2,
          "How often a raylet flushes its netted owner-local refcount "
          "deltas and scheduler stats to the GCS ledger.  Task results "
          "are NOT held to this cadence (the done flusher drains "
          "immediately when idle and batches only under load)."),
    _flag("raylet_spawn_headroom", 4,
          "Extra replacement workers a raylet may fork beyond its base "
          "pool while workers are blocked in get() with leased work "
          "queued (reference: raylet replacement workers for blocked "
          "ones; bounds nested-task deadlock avoidance)."),
    _flag("health_check_period_s", 1.0, "Control-plane node health check period."),
    _flag("health_check_timeout_s", 10.0, "Node declared dead after this long w/o heartbeat."),
    # --- tasks / actors ------------------------------------------------------
    _flag("task_default_max_retries", 3, "Default max_retries for tasks (-1 = infinite)."),
    _flag("actor_default_max_restarts", 0, "Default max_restarts for actors."),
    # --- collectives / TPU ---------------------------------------------------
    _flag("collective_chunk_bytes", 4 * 1024 * 1024,
          "Chunk size for DCN object-plane fallback collectives."),
    _flag("tpu_topology", "", "Override detected TPU topology (e.g. 'v4-8')."),
    _flag("tpu_workers_per_node", 1,
          "Device-holding worker processes per node (concurrent jax inits "
          "contend for the same chips; raise only with per-worker chip "
          "partitioning, e.g. TPU_VISIBLE_DEVICES plumbing)."),
    _flag("xla_cache_dir", "/tmp/rtpu_xla_cache",
          "Persistent XLA compilation cache shared across sessions and "
          "worker restarts (SURVEY.md §7.3: big-model compiles take "
          "minutes; Serve replica restarts and trainer elastic restarts "
          "must not pay them again).  '' disables."),
    # --- wire protocol -------------------------------------------------------
    _flag("proto_min_version", 0,
          "Minimum control-plane wire version the GCS accepts (0 = legacy "
          "raw-pickle peers allowed).  Raising it makes the server reject "
          "__proto_hello__ from older clients AND legacy frames — the "
          "version-skew guard the reference gets from protobuf/gRPC "
          "(src/ray/protobuf/).  See _private/wire.py."),
    # --- metrics / tracing ---------------------------------------------------
    _flag("metrics_enabled", True,
          "Always-on metrics plane: every non-client ray_tpu process runs "
          "a background publisher thread pushing its metric registry "
          "snapshot to the GCS KV, so `/metrics` and `ray_tpu metrics` "
          "show live built-in series with zero user wiring.  False "
          "disables both the publisher and built-in instrumentation "
          "(metrics.publish() still works manually)."),
    _flag("metrics_export_period_s", 5.0,
          "Background metrics publisher period (jittered per cycle; "
          "clamped to >= 1s so publishing stays off the task hot path)."),
    _flag("timeline_enabled", True, "Record profile events for `ray_tpu timeline`."),
    _flag("tsdb_enabled", True,
          "Head-resident metrics time-series store (DESIGN.md §4k): the "
          "GCS ingests every __metrics__/ snapshot it already receives "
          "into fixed-memory ring buffers with a downsampling ladder, "
          "queryable via the metrics_query op / state.metrics_history() "
          "/ `ray_tpu top` / the dashboard history endpoint, and feeds "
          "the always-on straggler + SLO burn-rate detectors.  Requires "
          "metrics_enabled."),
    _flag("tsdb_max_series", 4096,
          "Global series bound of the head TSDB (beyond it new series "
          "are dropped and counted, never grown — fixed memory)."),
    _flag("tsdb_raw_samples", 360,
          "Raw-rung ring slots per series (one per received publish; "
          "~30min of history at the 5s default export period before "
          "queries fall to the 30s/300s downsampled rungs)."),
    _flag("tsdb_detector_interval_s", 5.0,
          "How often the GCS monitor loop runs the TSDB anomaly "
          "detectors (train straggler skew + SLO burn rate)."),
    _flag("tsdb_straggler_window_s", 30.0,
          "Straggler detector sliding window: per-rank mean step time "
          "(Δsum/Δcount of rtpu_train_step_seconds) is compared to the "
          "group median over this window."),
    _flag("tsdb_straggler_ratio", 1.75,
          "A rank is a straggler when its window-mean step time "
          "exceeds this multiple of the group median (fires a "
          "'straggler' fleet event tagged with the rank's node)."),
    _flag("profiler_enabled", True,
          "Always-on sampling profiler (DESIGN.md §4o): every non-client "
          "process runs one jittered daemon thread at profiler_hz "
          "walking sys._current_frames() into a bounded folded-stack "
          "table; deltas ride the metrics-publisher cadence under the "
          "reserved __profile__/ KV prefix into the head ProfileStore, "
          "queryable via profile_query / state.profile() / "
          "`ray_tpu profile` / the dashboard /profile/flame endpoint."),
    _flag("profiler_hz", 10.0,
          "Sampling frequency of the always-on profiler (jittered per "
          "cycle; ~10Hz keeps the floor overhead under the 5% "
          "prof_bench bound while still resolving 100ms hot spots)."),
    _flag("profiler_max_stacks", 512,
          "Distinct folded stacks kept per publish window; beyond it "
          "new stacks fold into one '(overflow)' bucket (fixed "
          "memory, never grown)."),
    _flag("incident_max", 32,
          "Incident bundles kept under <session>/incidents/; beyond it "
          "the oldest bundle directories are evicted (bounded disk)."),
    _flag("incident_dedup_s", 300.0,
          "One incident bundle per node per this window: detector "
          "refires and the autopilot drain that follows them reuse the "
          "existing bundle id instead of capturing again."),
    # --- fleet autopilot (DESIGN.md §4n) -------------------------------------
    _flag("autopilot_enabled", False,
          "Head-side supervision loop closing the observability -> "
          "actuation gap (DESIGN.md §4n): straggler fleet events drain "
          "the offending host, drain warnings pre-warm replacement "
          "capacity, and the 48h TSDB demand history feeds a diurnal "
          "forecast to the autoscaler.  Every action is rate-limited, "
          "hysteresis-guarded, and emitted as a fleet event + "
          "rtpu_autopilot_actions_total sample."),
    _flag("autopilot_interval_s", 1.0,
          "How often the GCS monitor loop runs an autopilot reflex pass "
          "(event intake + periodic work)."),
    _flag("autopilot_drain_window_s", 300.0,
          "Autopilot drain rate-limit window: at most "
          "autopilot_max_drains_per_window remediation drains are "
          "issued per window, cluster-wide (a noisy detector must "
          "never cause a drain storm)."),
    _flag("autopilot_max_drains_per_window", 1,
          "Remediation drains the autopilot may issue per "
          "autopilot_drain_window_s."),
    _flag("autopilot_node_cooldown_s", 600.0,
          "Per-node relapse window: a node that stragglers again "
          "within this long of being returned to the pool is drained "
          "again IMMEDIATELY and permanently (the host is genuinely "
          "sick; operator/autoscaler replacement owns it).  Past the "
          "window the node starts fresh and a new drain is ordinary "
          "and recoverable."),
    _flag("autopilot_undrain_after_s", 120.0,
          "A straggler-drained node returns to the schedulable pool "
          "after this long without a fresh straggler signal (see "
          "autopilot_node_cooldown_s for what a relapse costs it)."),
    _flag("autopilot_prewarm", True,
          "Reflex 2: a node_draining warning pre-warms a replacement "
          "through the attached autoscaler DURING the warning window "
          "(the pre-warmed node is reserved against the incoming loss "
          "in _net_pending_capacity, so it is never double-launched)."),
    _flag("autopilot_forecast", True,
          "Reflex 3: feed the autoscaler a lead-time demand signal from "
          "a seasonal-naive forecast over the TSDB demand history, so "
          "it scales ahead of the diurnal curve instead of behind it."),
    _flag("autopilot_forecast_interval_s", 30.0,
          "How often the forecast reflex re-evaluates (two TSDB ladder "
          "scans + a demand scan per evaluation; the diurnal signal "
          "moves over minutes, not monitor ticks)."),
    _flag("autopilot_forecast_horizon_s", 120.0,
          "Forecast lead time (roughly node boot delay + one reconcile "
          "period: capacity requested now is ready when the predicted "
          "demand arrives)."),
    _flag("autopilot_forecast_period_s", 86400.0,
          "Seasonal period of the demand forecast (diurnal by "
          "default; the TSDB's 48h long rung holds two periods)."),
    _flag("autopilot_standby", True,
          "Reflex 4 (with autopilot_enabled): keep one warm GCS "
          "standby attached — launch `python -m "
          "ray_tpu._private.replication` when rtpu_gcs_repl_standbys "
          "== 0, re-launch on standby death, and emit an "
          "unprotected_head fleet event while the head is "
          "unreplicated.  Requires gcs_wal."),
    _flag("autopilot_standby_backoff_s", 5.0,
          "Minimum seconds between autopilot standby (re)launch "
          "attempts."),
    _flag("elastic_state_inline_max_bytes", 4 * 1024 * 1024,
          "Elastic gathered-state checkpoints at or below this ride "
          "the GCS KV inline (head-durable, restart-safe).  Larger "
          "states are published to the object plane and re-sharded "
          "peer-to-peer over the PR-4 streaming data plane instead of "
          "through the head (the KV holds only the ObjectRef; the "
          "manager adopts a borrow so the blob outlives the "
          "publishing worker)."),
    _flag("trace_sample_rate", 0.01,
          "Head-based sampling rate for automatically-rooted request "
          "traces (e.g. one Serve HTTP request = one candidate root). "
          "Explicit tracing.trace() spans are always sampled; children "
          "inherit the root's decision, so a sampled-out request costs "
          "one random() call cluster-wide.  0 disables auto roots."),
    _flag("flight_recorder_enabled", True,
          "Always-on per-process flight recorder: a fixed-size mmap ring "
          "buffer in the session dir recording recent wire frames, "
          "scheduler decisions, lock-watchdog waits, and engine "
          "iterations.  Crash-surviving by construction (the ring file "
          "outlives a SIGKILLed process); read it with "
          "`ray_tpu debug dump`."),
    _flag("flight_recorder_slots", 2048,
          "Ring-buffer capacity (records) per process; older records are "
          "overwritten in place (fixed memory, no growth)."),
]

_DEFS: Dict[str, _FlagDef] = {d.name: d for d in _FLAG_DEFS}


class RayTpuConfig:
    """Resolved config: defaults < env (RTPU_*) < _system_config dict."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._overrides: Dict[str, Any] = {}

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        d = _DEFS.get(name)
        if d is None:
            raise AttributeError(f"unknown ray_tpu config flag: {name!r}")
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        env = os.environ.get(_ENV_PREFIX + name.upper())
        if env is not None:
            return d.type(env)
        return d.default

    def apply_system_config(self, system_config: Optional[Dict[str, Any]]) -> None:
        if not system_config:
            return
        with self._lock:
            for k, v in system_config.items():
                if k not in _DEFS:
                    raise ValueError(f"unknown _system_config key: {k!r}")
                self._overrides[k] = v

    def snapshot(self) -> Dict[str, Any]:
        """Full resolved view (for propagation to child processes / debugging)."""
        return {name: getattr(self, name) for name in _DEFS}

    def apply_xla_cache_env(self, env: Dict[str, str]) -> None:
        """Point a process (driver, spawned worker, bench) at the
        persistent XLA compile cache — the single place that knows the
        env-var spelling."""
        if self.xla_cache_dir:
            env.setdefault("JAX_COMPILATION_CACHE_DIR", self.xla_cache_dir)

    def to_env(self) -> Dict[str, str]:
        """Encode the resolved config as RTPU_* env vars for child processes."""
        out = {}
        for name, val in self.snapshot().items():
            out[_ENV_PREFIX + name.upper()] = (
                json.dumps(val) if isinstance(val, bool) else str(val)
            )
        return out

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()


GLOBAL_CONFIG = RayTpuConfig()
