"""Memory monitor: kill tasks under node memory pressure.

Reference: ``MemoryMonitor`` (``src/ray/util/``, wired into the raylet —
SURVEY.md §2.1 Util row): when a node's memory usage crosses a threshold,
the worker running the most-recently-started retriable task is killed and
the task fails with an OOM error that counts against ``max_retries`` —
preferring a targeted, retriable kill over the kernel OOM killer taking
out the raylet or an actor.

Policy here (matching the reference's task-killing policy shape):
- usage = used/total from cgroup v2 (``memory.current``/``memory.max``)
  when限 bounded, else ``/proc/meminfo`` (MemTotal - MemAvailable).
- above ``memory_usage_threshold`` → kill the LAST-STARTED running task's
  worker (newest-first: it has made the least progress and is likeliest
  part of the pressure spike); actors are never chosen (reference
  behavior: workers running retriable work first).
- the killed task is failed with ``OutOfMemoryError`` (retriable if the
  task has retries left — at-least-once, like any worker death).
"""

from __future__ import annotations

import os
from typing import Tuple

from ray_tpu._private import rtlog

logger = rtlog.get("memory-monitor")

_CGROUP = "/sys/fs/cgroup"


def node_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) — cgroup v2 when memory-limited, else
    system-wide from /proc/meminfo."""
    try:
        raw_max = open(os.path.join(_CGROUP, "memory.max")).read().strip()
        if raw_max != "max":
            used = int(open(os.path.join(_CGROUP,
                                         "memory.current")).read())
            return used, int(raw_max)
    except (OSError, ValueError):
        pass
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        return 0, 0
    return max(0, total - avail), total


def _pid_is_local_worker(pid: int) -> bool:
    """True only when ``pid`` is a ray_tpu worker process on THIS host —
    the proof required before os.kill'ing a pid the head didn't spawn."""
    if not pid:
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"ray_tpu._private.worker_main" in f.read()
    except OSError:
        return False


def pick_oom_victim(gcs, node_id=None, require_proc=False):
    """Newest-started plain task worker (never actors, never the driver),
    optionally restricted to one node / to head-spawned (proc-backed)
    workers.  Shared by the head-local monitor and the per-node agent
    path (reference: MemoryMonitor runs per-node inside the raylet)."""
    with gcs.lock:
        candidates = []
        for w in gcs.workers.values():
            if w.state != "busy" or w.current_task is None:
                continue
            if node_id is not None and w.node_id != node_id:
                continue
            if require_proc and w.proc is None:
                continue
            spec = w.current_task
            if spec.get("is_actor_creation"):
                continue
            candidates.append((spec.get("_started_at", 0.0), w, spec))
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[0])
        _, w, spec = candidates[-1]
        return w, spec


class MemoryMonitor:
    """Periodic check invoked from the GCS monitor loop.

    Scope: the HEAD machine only.  The usage signal below is read from
    this host's cgroup//proc/meminfo, so eligible victims are workers the
    head itself spawned (``w.proc is not None``) — plus proc-less workers
    on the head node whose pid is VERIFIED to be a local worker process
    (reattached survivors of a GCS restart; ``_pid_is_local_worker``).
    A proc-less WorkerState can otherwise belong to a remote NodeAgent
    whose pid lives in another host's pid namespace; ``os.kill`` on it
    from here would hit an arbitrary unrelated local process.  Remote
    hosts run their own monitor inside the NodeAgent (node_agent.py),
    which measures local pressure and kills pids it owns, with victim
    policy still decided here via the ``pick_oom_victim`` RPC."""

    def __init__(self, gcs):
        self.gcs = gcs
        self._last_check = 0.0
        self.kills = 0

    def maybe_kill(self, now: float) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        threshold = GLOBAL_CONFIG.memory_usage_threshold
        if threshold >= 1.0 or threshold <= 0:
            return  # disabled
        if now - self._last_check < GLOBAL_CONFIG.memory_monitor_interval_s:
            return
        self._last_check = now
        used, total = node_memory_usage()
        if not total or used / total < threshold:
            return
        victim = pick_oom_victim(self.gcs, require_proc=True)
        if victim is None:
            # Workers that reattached after a GCS restart are proc-less
            # but still local to this host: their pid is killable IF we
            # can prove it is really one of our worker processes (guards
            # against remote-agent pids from another host's namespace,
            # which reattach may have adopted onto the head node).
            victim = pick_oom_victim(self.gcs,
                                     node_id=self.gcs.head_node_id)
            if victim is not None and not _pid_is_local_worker(
                    victim[0].pid):
                victim = None
        if victim is None:
            logger.warning(
                "memory pressure %.0f%% above threshold %.0f%% but no "
                "killable head-local task worker (actors are exempt; "
                "remote workers are their agent's responsibility)",
                100 * used / total, 100 * threshold)
            return
        w, spec = victim
        logger.warning(
            "node memory %.0f%% >= %.0f%%: killing newest task %s "
            "(worker %s pid=%s) — reference MemoryMonitor policy",
            100 * used / total, 100 * threshold,
            spec.get("name", spec["task_id"]), w.worker_id[:8], w.pid)
        self.kills += 1
        spec["_oom_killed"] = True
        try:
            if w.proc is not None:
                w.proc.kill()
            else:  # verified-local reattached worker (see above)
                os.kill(w.pid, 9)
        except OSError:
            pass
        # death handling (retry bookkeeping, resource release, respawn)
        # rides the normal worker-death path via the monitor loop

    def _pick_victim(self):
        """Back-compat shim for tests: head-side victim policy."""
        return pick_oom_victim(self.gcs, require_proc=True)
