"""Raylet: the per-node local scheduler (DESIGN.md §4i).

Reference analog: ``src/ray/raylet/`` — ``NodeManager`` +
``LocalTaskManager`` (SURVEY.md §2).  The GCS stays the cluster's
*ledger* (placement policy, resource accounting, object directory, actor
FSM, placement groups, fault recovery, autoscaler feed); the raylet owns
the node's *hot path*:

- **Bulk lease claims.**  The GCS grants blocks of task specs (each spec
  = one worker lease, resources debited on the ledger at grant) in ONE
  ``lease_grant`` frame per scheduling pump instead of one push per
  task.  Plain-CPU specs beyond the node's resource fit ride the same
  frame as *queued* leases (``_lease_q``): they hold no ledger
  resources and start either by inheriting a finishing same-shape
  task's claim (handoff — the ledger moves the claim) or directly on
  an idle worker (pool-bounded local CPU oversubscription; nothing is
  ever released that was not acquired, so the ledger self-corrects at
  settlement).
- **Local dispatch + lease reuse.**  Workers attach their task/ctl
  connections to the raylet's unix socket, not the head.  A finishing
  task hands its lease to a queued same-shape spec and the worker runs
  it immediately — no head round-trip; the GCS hears about the handoff
  in the next ``raylet_done_batch`` entry (``next_task_id``) and moves
  the claim on the ledger after the fact.
- **Owner-local refcount batches.**  Workers route ``release`` /
  ``release_batch`` oneways to the raylet, which NETS them per client
  ledger and reconciles to the GCS every
  ``raylet_reconcile_interval_s`` as one ``raylet_ref_batch``.  Only
  releases ride this path — delaying a release is categorically safe
  (it can only delay a free); pins keep their direct ordering.
- **One keepalive.**  The lease channel doubles as node liveness
  (``raylet_heartbeat`` carries local scheduler stats); its EOF makes
  the GCS reclaim every outstanding lease and remove the node.  A clean
  shutdown instead returns unstarted leases (``raylet_lease_return``)
  and detaches (``raylet_detach``) so nothing waits on death detection.

Every lease frame is version-fenced: the raylet only attaches after the
``__proto_hello__`` negotiates ``wire.PROTO_RAYLET``; against an older
head :class:`RayletUnsupported` makes the NodeAgent fall back to the
legacy direct-GCS worker pool, byte-identical on the wire.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ray_tpu._private import lock_watchdog, protocol, rtlog, wire
from ray_tpu._private.config import GLOBAL_CONFIG

logger = rtlog.get("raylet")


class RayletUnsupported(RuntimeError):
    """The head does not speak PROTO_RAYLET: run the legacy agent path."""


class _Slot:
    """One local worker's scheduling state."""

    def __init__(self, worker_id: str, conn):
        self.worker_id = worker_id
        self.conn = conn              # task push channel (raylet-owned)
        self.conn_lock = threading.Lock()
        self.ctl_conn = None          # OOB channel (cancel / dump_stack)
        self.ctl_conn_lock = threading.Lock()
        self.state = "idle"           # idle|busy|actor|dead
        self.current: Optional[dict] = None
        self.blocked = False

    def push(self, msg: dict) -> bool:
        with self.conn_lock:
            if self.conn is None:
                return False
            try:
                self.conn.send(msg)
                return True
            except (OSError, ValueError):
                return False

    def push_ctl(self, msg: dict) -> bool:
        with self.ctl_conn_lock:
            conn = self.ctl_conn
            if conn is not None:
                try:
                    conn.send(msg)
                    return True
                except (OSError, ValueError):
                    self.ctl_conn = None
        return self.push(msg)


class Raylet:
    """Per-node local scheduler.  Owns the upstream lease channel (an
    already-negotiated >= PROTO_RAYLET connection handed over by the
    NodeAgent) and a local unix listener workers attach to."""

    def __init__(self, head, node_id: str, node_info: dict, sock_dir: str,
                 spawn_cb: Callable[[], None],
                 on_lost: Callable[[], None],
                 upstream_conn=None, upstream_version: int = 0):
        # rtlint: owns(upstream_conn)
        self.head = head
        self.node_id = node_id
        self._node_info = dict(node_info)  # add_node fields for re-join
        self._spawn_cb = spawn_cb
        self._on_lost = on_lost
        if upstream_conn is None:
            upstream_conn, upstream_version = self._dial_upstream()
        elif upstream_version < wire.PROTO_RAYLET:
            raise RayletUnsupported(
                f"head speaks v{upstream_version} < v{wire.PROTO_RAYLET}")
        self._proto = upstream_version
        # --- lock domains (rtlint: RAYLET_LOCK_DAG in lock_watchdog.py) ---
        # _lock guards the scheduler tables; worker pushes deliberately
        # ride it (bounded local-pipe sends, like the GCS's
        # task_conn_lock).  _up_lock serializes upstream channel sends
        # and is NEVER held together with _lock: flushers collect under
        # _lock, send under _up_lock.
        self._lock = threading.Lock()
        self._up_lock = threading.Lock()
        self._up_conn = upstream_conn    # guarded by: _up_lock
        self.sock_path = os.path.join(sock_dir, "raylet.sock")
        self._stop = threading.Event()
        self._queue: deque = deque()                 # guarded by: _lock
        self._slots: Dict[str, _Slot] = {}           # guarded by: _lock
        self._idle: deque = deque()                  # guarded by: _lock
        self._done_batch: List[dict] = []            # guarded by: _lock
        # local worker deaths awaiting upstream report (flushed with
        # the done batch so the death never races its failed spec)
        self._dead_reports: List[str] = []           # guarded by: _lock
        # client ledger -> oid -> pending release count
        self._ref_net: Dict[str, Dict[str, int]] = {}  # guarded by: _lock
        self._stats = {"granted": 0, "dispatched": 0, "done": 0,
                       "handoffs": 0, "ref_ops_netted": 0,
                       "ref_ops_forwarded": 0}       # guarded by: _lock
        self._spawned_extra = 0                      # guarded by: _lock
        self._last_reconcile = time.monotonic()      # guarded by: _lock
        self._done_event = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener = protocol.make_listener(self.sock_path)
        try:
            self._send_up("raylet_attach", node_id=self.node_id)
            for target, name in ((self._upstream_loop, "raylet-upstream"),
                                 (self._accept_loop, "raylet-accept"),
                                 (self._done_flush_loop, "raylet-done-flush"),
                                 (self._reconcile_loop, "raylet-reconcile")):
                t = threading.Thread(target=target, name=name, daemon=True)
                t.start()
                self._threads.append(t)
        except BaseException:
            # a half-built raylet must strand neither the listener nor
            # the upstream conn (NodeAgent retries / falls back)
            self._listener.close()
            try:
                upstream_conn.close()
            except OSError:
                pass
            raise
        logger.info("raylet up for node %s (proto v%d, sock %s)",
                    node_id[:8], self._proto, self.sock_path)

    # ------------------------------------------------------------ upstream
    def _dial_upstream(self):
        """Fresh negotiated lease channel to the head (reconnects)."""
        conn = protocol.tunnel_connect(*self.head, "gcs")
        try:
            ch = protocol.RpcChannel(conn)
            ver = ch.negotiate()
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise
        if ver < wire.PROTO_RAYLET:
            try:
                conn.close()
            except OSError:
                pass
            raise RayletUnsupported(
                f"head speaks v{ver} < v{wire.PROTO_RAYLET}")
        return conn, ver

    def _send_up(self, kind: str, **fields) -> None:
        msg = {"kind": kind, "rid": None, **fields}
        with self._up_lock:
            conn = self._up_conn
            if conn is None:
                raise OSError("upstream lease channel down")
            wire.conn_send(conn, msg, self._proto)

    def _send_up_safe(self, kind: str, **fields) -> bool:
        try:
            self._send_up(kind, **fields)
            return True
        except (OSError, ValueError, EOFError):
            return False

    def _upstream_loop(self) -> None:
        """Read GCS pushes; on EOF re-join the (possibly restarted) head
        — re-add the node, re-announce the worker roster, and let the
        flushers re-report unsettled results and un-reconciled refcount
        deltas (the ledger-delta half of GCS fault tolerance)."""
        while not self._stop.is_set():
            with self._up_lock:
                conn = self._up_conn
            if conn is None:
                if not self._reconnect_upstream():
                    return
                continue
            try:
                # rtlint: blocks-ok(parks until the head pushes; head
                # death EOFs the channel and the reconnect loop's
                # jittered backoff (cap 0.5s) is the re-dial deadline)
                msg, _ = wire.conn_recv(conn)
            except (EOFError, OSError, wire.WireError):
                if self._stop.is_set():
                    return
                with self._up_lock:
                    if self._up_conn is conn:
                        self._up_conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                self._handle_push(msg)
            except Exception:  # noqa: BLE001 - one bad frame must not
                # kill the only grant reader
                logger.exception("raylet push failed: %s", msg.get("kind"))

    def _reconnect_upstream(self) -> bool:
        """Re-join the head after a lease-channel EOF.  Returns False
        when the grace expires (the node is then torn down)."""
        from ray_tpu._private import flight_recorder
        deadline = time.monotonic() + GLOBAL_CONFIG.gcs_reconnect_timeout_s
        logger.warning("lost lease channel to head; rejoining for up "
                       "to %.0fs", GLOBAL_CONFIG.gcs_reconnect_timeout_s)
        # jittered backoff (protocol.backoff_delays): a fleet of raylets
        # re-joining a promoted standby must not dial in lockstep
        delays = protocol.backoff_delays(cap=0.5, base=0.05)
        while not self._stop.is_set() and time.monotonic() < deadline:
            conn = None
            try:
                conn, ver = self._dial_upstream()
                ch = protocol.RpcChannel(conn)
                ch.version = ver
                resp = ch.call("add_node", **self._node_info)
                self.node_id = resp["node_id"]
                msg = {"kind": "raylet_attach", "rid": None,
                       "node_id": self.node_id}
                wire.conn_send(conn, msg, ver)
                with self._lock:
                    roster = [{"worker_id": wid}
                              for wid, s in self._slots.items()
                              if s.state != "dead"]
                roster_msg = {"kind": "raylet_workers", "rid": None,
                              "node_id": self.node_id, "workers": roster}
                wire.conn_send(conn, roster_msg, ver)
                with self._up_lock:
                    self._up_conn = conn
                    self._proto = ver
                flight_recorder.record("raylet", "rejoined head as "
                                       + self.node_id[:8])
                logger.info("rejoined head as node %s; re-reporting "
                            "ledger deltas", self.node_id[:8])
                # unsettled results + netted refs re-flush on the new
                # channel (at-least-once, the documented FT contract)
                self._done_event.set()
                self._flush_refs()
                return True
            except RayletUnsupported:
                break  # a DOWNGRADED head: no lease protocol anymore
            except (OSError, EOFError, ConnectionError, Exception):  # noqa: BLE001
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                with lock_watchdog.bounded_block(
                        "raylet.reconnect_backoff"):
                    stopped = self._stop.wait(next(delays))
                if stopped:
                    return False
        if not self._stop.is_set():
            logger.error("could not rejoin head; shutting down node")
            self._on_lost()
        return False

    # ------------------------------------------------------- GCS -> raylet
    def _handle_push(self, msg: dict) -> None:
        kind = msg.get("kind")
        from ray_tpu._private import flight_recorder
        if flight_recorder.enabled() and kind != "lease_grant":
            flight_recorder.record("raylet_push", str(kind))
        if kind == "lease_grant":
            specs = msg.get("specs", ())
            if flight_recorder.enabled():
                flight_recorder.record("lease_grant", f"n={len(specs)}")
            with self._lock:
                self._stats["granted"] += len(specs)
                self._queue.extend(specs)
                self._dispatch_locked()
            self._maybe_spawn_extra()
        elif kind == "lease_revoke":
            ids = set(msg.get("task_ids", ()))
            run_cancel: List[tuple] = []
            with self._lock:
                self._queue = deque(s for s in self._queue
                                    if s["task_id"] not in ids)
                for s in self._slots.values():
                    if s.current is not None \
                            and s.current.get("task_id") in ids:
                        # capture the id UNDER the lock: a handoff may
                        # re-fill the slot before the ctl push, and the
                        # successor must not eat the cancel
                        run_cancel.append((s, s.current["task_id"]))
            for s, tid in run_cancel:
                s.push_ctl({"kind": "cancel", "task_id": tid})
        elif kind == "worker_ctl":
            with self._lock:
                slot = self._slots.get(msg.get("worker_id"))
            if slot is not None:
                slot.push_ctl(msg.get("msg", {}))
        elif kind == "raylet_stop":
            self._on_lost()

    # ------------------------------------------------------ local scheduler
    def _dispatch_locked(self) -> None:
        """_lock held.  Start leases on idle workers.  Funded specs
        first (their claims are on the ledger); queued ``_lease_q``
        specs may ALSO start on an idle worker — concurrency is bounded
        by the worker pool itself, so this is at most a bounded local
        CPU oversubscription on the ledger (the piggyback argument,
        node-scoped), and the settlement path self-corrects: an
        unfunded spec carries no ``_req``, so nothing is ever released
        that was not acquired.  Waiting for funding instead would idle
        a worker for a reconcile round-trip per chain break."""
        while self._idle and self._queue:
            spec = None
            for _ in range(len(self._queue)):
                cand = self._queue.popleft()
                if cand.get("_lease_q"):
                    self._queue.append(cand)
                    continue
                spec = cand
                break
            if spec is None:
                spec = self._queue.popleft()  # queued lease: start it
            slot = self._slots.get(self._idle.popleft())
            if slot is None or slot.state != "idle":
                self._queue.appendleft(spec)
                continue
            self._start_on_locked(slot, spec)

    def _start_on_locked(self, slot: _Slot, spec: dict) -> None:
        """_lock held.  Push one spec to a worker (push rides _lock by
        design — a bounded local-pipe send, like GCS task pushes)."""
        slot.state = "busy"
        slot.current = spec
        self._stats["dispatched"] += 1
        kind = ("create_actor" if spec.get("is_actor_creation")
                else "execute_task")
        if not slot.push({"kind": kind, "spec": spec, "dseq": 0,
                          "queued": []}):
            self._worker_died_locked(slot)

    def _take_handoff_locked(self, spec: dict) -> Optional[dict]:
        """_lock held.  A queued lease that can inherit ``spec``'s claim
        (same resource shape — the GCS granted it against this chain).
        PG-funded specs never hand off: their claim lives on the PG
        bundle, not the node ledger."""
        req = spec.get("_req")
        if req is None or spec.get("_pg_claim") is not None:
            return None
        for _ in range(len(self._queue)):
            cand = self._queue.popleft()
            if cand.get("_lease_q") and cand.get("_lease_shape") == req:
                return cand
            self._queue.append(cand)
        return None

    def _maybe_spawn_extra(self) -> None:
        """Replacement workers while the pool is blocked in get() with
        leased work queued (reference: raylet spawns replacements for
        blocked workers — bounded, or nested task chains deadlock)."""
        with self._lock:
            if not self._queue or self._stop.is_set():
                return
            free = any(s.state == "idle" for s in self._slots.values())
            unblocked_busy = any(s.state == "busy" and not s.blocked
                                 for s in self._slots.values())
            if free or unblocked_busy:
                return
            if self._spawned_extra >= GLOBAL_CONFIG.raylet_spawn_headroom:
                return
            self._spawned_extra += 1
        try:
            self._spawn_cb()
        except Exception:  # noqa: BLE001 - spawn is best-effort
            logger.exception("replacement worker spawn failed")

    # ------------------------------------------------------ worker channel
    def _accept_loop(self) -> None:
        protocol.serve_accept_loop(self._listener,
                                   lambda: self._stop.is_set(),
                                   self._serve_conn, "raylet-serve-conn")

    def _serve_conn(self, conn) -> None:
        """One local connection: a worker's task channel, ctl channel, or
        refcount channel — decided by its first frame."""
        try:
            try:
                # rtlint: blocks-ok(a dialer writes its attach frame in
                # the same breath as the dial; one that dies first EOFs
                # here — worker liveness is the deadline)
                first = conn.recv()
            except (EOFError, OSError):
                return
            kind = first.get("kind")
            if kind == "attach_task_conn":
                self._worker_loop(first["worker_id"], conn)
                return  # _worker_loop owns + closes the conn
            if kind == "attach_worker_ctl":
                self._ctl_park(first["worker_id"], conn)
                return
            if kind == "ref_chan":
                self._ref_loop(conn)
                return
            logger.warning("unknown raylet attach kind %r", kind)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _ctl_park(self, worker_id: str, conn) -> None:
        with self._lock:
            slot = self._slots.get(worker_id)
            if slot is not None:
                with slot.ctl_conn_lock:
                    slot.ctl_conn = conn
        while not self._stop.is_set():
            try:
                # rtlint: blocks-ok(parks for the worker's lifetime;
                # worker death EOFs its ctl pipe — the monitored-process
                # exit IS the deadline, same contract as _worker_loop)
                conn.recv()
            except (EOFError, OSError):
                break
        with self._lock:
            slot = self._slots.get(worker_id)
        if slot is not None:
            with slot.ctl_conn_lock:
                if slot.ctl_conn is conn:
                    slot.ctl_conn = None

    def _ref_loop(self, conn) -> None:
        """Net release oneways from a local worker.  +N releases of one
        oid collapse to a count; the reconcile loop ships the batch."""
        while not self._stop.is_set():
            try:
                # rtlint: blocks-ok(parks between a local worker's
                # release oneways; worker death EOFs the pipe and the
                # reconcile loop settles whatever was already netted)
                msg = conn.recv()
            except (EOFError, OSError):
                return
            kind = msg.get("kind")
            client = msg.get("client_id")
            with self._lock:
                net = self._ref_net.setdefault(client, {})
                if kind == "release":
                    net[msg["object_id"]] = net.get(msg["object_id"], 0) + 1
                    self._stats["ref_ops_netted"] += 1
                elif kind == "release_batch":
                    oids = msg.get("object_ids", ())
                    for oid in oids:
                        net[oid] = net.get(oid, 0) + 1
                    # per-oid count, same unit as ref_ops_forwarded —
                    # the netted/forwarded ratio is the collapse factor
                    self._stats["ref_ops_netted"] += len(oids)
                else:
                    # anything else is a contract violation of the
                    # worker-side router; drop loudly rather than
                    # corrupt the ledger
                    logger.warning("non-release kind %r on ref channel",
                                   kind)

    def _worker_loop(self, worker_id: str, conn) -> None:
        slot = _Slot(worker_id, conn)
        with self._lock:
            old = self._slots.get(worker_id)
            if old is not None and old.state != "dead":
                self._worker_died_locked(old)
            self._slots[worker_id] = slot
            self._idle.append(worker_id)
            self._dispatch_locked()
        logger.info("worker %s attached", worker_id[:8])
        while not self._stop.is_set():
            try:
                # rtlint: blocks-ok(parks between a local worker's task
                # events; worker death EOFs the pipe and the slot is
                # reaped below — process liveness is the deadline)
                msg = conn.recv()
            except (EOFError, OSError):
                break
            try:
                self._on_worker_event(slot, msg)
            except Exception:  # noqa: BLE001 - keep the channel alive
                logger.exception("worker event failed: %s", msg.get("kind"))
        with self._lock:
            if self._slots.get(worker_id) is slot and slot.state != "dead":
                self._worker_died_locked(slot)

    def _on_worker_event(self, slot: _Slot, msg: dict) -> None:
        kind = msg.get("kind")
        if kind == "task_done":
            self._on_task_done(slot, msg)
        elif kind == "task_blocked":
            with self._lock:
                slot.blocked = True
                spec = slot.current
            if spec is not None:
                self._send_up_safe("raylet_task_blocked",
                                   node_id=self.node_id,
                                   task_id=spec.get("task_id"))
            self._maybe_spawn_extra()
        elif kind == "task_unblocked":
            with self._lock:
                slot.blocked = False
                spec = slot.current
            if spec is not None:
                self._send_up_safe("raylet_task_unblocked",
                                   node_id=self.node_id,
                                   task_id=spec.get("task_id"))
        elif kind == "actor_ready":
            with self._lock:
                if msg.get("status") == "ok" or msg.get("reattach"):
                    slot.state = "actor"
                    slot.current = None
                else:
                    # creation failed: the worker returns to its task
                    # loop; give the slot back to the local pool
                    slot.state = "idle"
                    slot.current = None
                    self._idle.append(slot.worker_id)
                    self._dispatch_locked()
            self._send_up_safe("raylet_fwd", node_id=self.node_id,
                               worker_id=slot.worker_id, msg=msg)
        else:
            # actor_result / actor_exit / stack_dump / log /
            # profile_events: the GCS's worker-event machinery handles
            # these unchanged — forward verbatim
            self._send_up_safe("raylet_fwd", node_id=self.node_id,
                               worker_id=slot.worker_id, msg=msg)

    def _on_task_done(self, slot: _Slot, msg: dict) -> None:
        from ray_tpu._private import flight_recorder
        with self._lock:
            spec = slot.current
            if spec is None or spec.get("task_id") != msg.get("task_id"):
                return
            slot.current = None
            entry = {"task_id": msg["task_id"], "status": msg["status"],
                     "results": msg.get("results"),
                     "error": msg.get("error"),
                     "events": msg.get("events"),
                     "return_ids": list(spec.get("return_ids", ()))}
            self._stats["done"] += 1
            # lease reuse: a queued same-shape spec inherits this claim
            # and starts NOW — zero head round-trips on the chain
            nxt = self._take_handoff_locked(spec)
            if nxt is not None:
                entry["next_task_id"] = nxt["task_id"]
                nxt.pop("_lease_q", None)
                nxt.pop("_lease_shape", None)
                nxt["_req"] = spec.get("_req")
                self._stats["handoffs"] += 1
                self._start_on_locked(slot, nxt)
            elif slot.state == "busy":
                slot.state = "idle"
                self._idle.append(slot.worker_id)
                self._dispatch_locked()
            self._done_batch.append(entry)
        if flight_recorder.enabled():
            flight_recorder.record(
                "raylet_done", f"{msg['task_id'][:16]} {msg['status']}"
                               f"{' handoff' if 'next_task_id' in entry else ''}")
        self._done_event.set()

    def _worker_died_locked(self, slot: _Slot) -> None:
        """_lock held.  Report the death + the running spec upstream;
        the NodeAgent's pool loop respawns the process."""
        if slot.state == "dead":
            return
        slot.state = "dead"
        with slot.conn_lock:
            slot.conn = None
        try:
            self._idle.remove(slot.worker_id)
        except ValueError:
            pass
        spec = slot.current
        slot.current = None
        if spec is not None:
            self._done_batch.append(
                {"task_id": spec["task_id"], "status": "worker_died",
                 "return_ids": list(spec.get("return_ids", ()))})
        # the death notice rides the done flusher (never sent under
        # _lock: upstream sends stay outside the scheduler's critical
        # section), AFTER the failed spec's entry so the head observes
        # them in causal order
        self._dead_reports.append(slot.worker_id)
        self._slots.pop(slot.worker_id, None)
        self._done_event.set()

    # --------------------------------------------------------- reconcilers
    def _done_flush_loop(self) -> None:
        """Ship completed leases upstream.  Drains IMMEDIATELY when the
        node is quiet (serial latency) and coalesces adaptively under
        load: once a drain carries several entries, the next drain
        waits a beat so settlement batches (and the head's per-batch
        lock acquisitions) grow instead of degenerating to one frame
        per task."""
        busy = False
        while not self._stop.is_set():
            with lock_watchdog.bounded_block("raylet.done_flush_tick"):
                self._done_event.wait(1.0)
            if self._stop.is_set():
                return
            if busy:
                time.sleep(0.005)  # coalesce window under load only
            self._done_event.clear()
            with self._lock:
                n = len(self._done_batch)
            busy = n >= 4
            self._flush_done()

    def _flush_done(self) -> None:
        with self._lock:
            if not self._done_batch and not self._dead_reports:
                return
            batch, self._done_batch = self._done_batch, []
            deaths, self._dead_reports = self._dead_reports, []
        ok = True
        if batch:
            ok = self._send_up_safe("raylet_done_batch",
                                    node_id=self.node_id, entries=batch)
        if ok:
            for wid in deaths:
                self._send_up_safe("raylet_worker_died",
                                   node_id=self.node_id, worker_id=wid)
        else:
            # channel down: retain for the post-reconnect re-flush
            with self._lock:
                self._done_batch[:0] = batch
                self._dead_reports[:0] = deaths

    def _flush_refs(self) -> None:
        with self._lock:
            if not any(self._ref_net.values()):
                self._last_reconcile = time.monotonic()
                return
            net, self._ref_net = self._ref_net, {}
        ops = []
        n_ops = 0
        for client, oids in net.items():
            object_ids = []
            for oid, cnt in oids.items():
                object_ids.extend([oid] * cnt)
                n_ops += cnt
            if object_ids:
                ops.append(["release_batch",
                            {"client_id": client, "object_ids": object_ids}])
        if not ops:
            return
        if self._send_up_safe("raylet_ref_batch", node_id=self.node_id,
                              ops=ops, netted=n_ops):
            with self._lock:
                self._stats["ref_ops_forwarded"] += n_ops
                self._last_reconcile = time.monotonic()
        else:
            with self._lock:  # merge back for the re-flush
                for client, oids in net.items():
                    cur = self._ref_net.setdefault(client, {})
                    for oid, cnt in oids.items():
                        cur[oid] = cur.get(oid, 0) + cnt

    def _reconcile_loop(self) -> None:
        period = max(0.05, GLOBAL_CONFIG.raylet_reconcile_interval_s)
        while not self._stop.wait(period):
            self._flush_refs()
            with self._lock:
                stats = dict(self._stats)
                stats["queued"] = len(self._queue)
                stats["idle"] = len(self._idle)
                stats["busy"] = sum(1 for s in self._slots.values()
                                    if s.state == "busy")
                stats["blocked"] = sum(1 for s in self._slots.values()
                                       if s.blocked)
                age = time.monotonic() - self._last_reconcile
            self._send_up_safe("raylet_heartbeat", node_id=self.node_id,
                               stats=stats, reconcile_age=age)

    # -------------------------------------------------------------- stop
    def stop(self) -> None:
        """Clean leave: flush every pending report, RETURN unstarted
        leases, and detach — the GCS reclaims nothing by death-detection
        (the satellite contract: shutdown hands the ledger back)."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._flush_done()
        self._flush_refs()
        with self._lock:
            queued = [s["task_id"] for s in self._queue]
            self._queue.clear()
        if queued:
            self._send_up_safe("raylet_lease_return",
                               node_id=self.node_id, task_ids=queued)
        self._send_up_safe("raylet_detach", node_id=self.node_id)
        with self._up_lock:
            conn, self._up_conn = self._up_conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
        for s in slots:
            with s.conn_lock:
                conn, s.conn = s.conn, None
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            with s.ctl_conn_lock:
                ctl, s.ctl_conn = s.ctl_conn, None
            if ctl is not None:
                try:
                    ctl.close()
                except OSError:
                    pass
        logger.info("raylet stopped (returned %d queued leases)",
                    len(queued))
