"""Entry point for spawned worker processes.

Reference: the worker-process half of ``python/ray/_private/workers`` startup
(``default_worker.py``): connect to the node's control plane, register, then
serve the task loop until stopped.
"""

from __future__ import annotations

import io
import os
import sys


class _LogShipper(io.TextIOBase):
    """Tee worker stdout/stderr to the driver via the control plane."""

    def __init__(self, worker, stream_name: str, orig):
        self.worker = worker
        self.stream_name = stream_name
        self.orig = orig
        self._buf = ""

    def write(self, s: str) -> int:
        self.orig.write(s)
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            if line.strip():
                task = self.worker._current_spec or {}
                prefix = task.get("name") or task.get("class_name") or "worker"
                self.worker._send_event({
                    "kind": "log",
                    "line": f"({prefix} pid={os.getpid()}) {line}"})
        return len(s)

    def flush(self) -> None:
        self.orig.flush()


def main() -> None:
    from ray_tpu._private import resource_sanitizer, rtlog
    from ray_tpu._private.session import Session
    from ray_tpu._private.worker import Worker, set_global_worker
    from ray_tpu._private.config import GLOBAL_CONFIG

    # leak oracle (env rides Popen inheritance from the head): every
    # acquisition from here on must be discharged by the clean-stop
    # path below
    resource_sanitizer.maybe_install()

    node_id = os.environ["RTPU_NODE_ID"]
    proxy = os.environ.get("RTPU_PROXY_ADDR")
    if proxy:
        # remote-node worker (spawned by a NodeAgent on another host):
        # RPCs tunnel to the head; no local session/data plane.  On a
        # raylet node (RTPU_RAYLET_SOCK set) the task/ctl channels and
        # release oneways instead attach to the LOCAL per-node scheduler
        # (Worker reads the env; see _dial_task_endpoint / §4i).
        from ray_tpu._private import protocol
        protocol.set_authkey_from_env()
        host, _, port = proxy.partition(":")
        rtlog.setup("worker", None)
        session = None
        worker = Worker(None, role="worker", node_id=node_id,
                        proxy_addr=(host, int(port)))
    else:
        session_dir = os.environ["RTPU_SESSION_DIR"]
        root, name = os.path.split(session_dir)
        session = Session(root=root, name=name)
        from ray_tpu._private import protocol
        protocol.set_authkey(session.auth_key())
        rtlog.setup("worker", session.log_dir)
        worker = Worker(session, role="worker", node_id=node_id)
    set_global_worker(worker)
    if GLOBAL_CONFIG.log_to_driver:
        sys.stdout = _LogShipper(worker, "stdout", sys.stdout)
        sys.stderr = _LogShipper(worker, "stderr", sys.stderr)
    worker.run_worker_loop()
    # only a CLEAN stop reaches here (stop_worker / head-gone exit);
    # SIGTERM/SIGKILL teardown never does — the oracle asserts exactly
    # the paths the static pass models
    if resource_sanitizer.sanitizer_enabled():
        worker.shutdown()
        resource_sanitizer.assert_clean_at_shutdown("worker-exit")


if __name__ == "__main__":
    main()
