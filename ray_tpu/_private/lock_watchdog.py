"""Canonical lock-order DAGs + an opt-in runtime lock-order watchdog.

DESIGN.md §4c documents the GCS locking discipline in prose; this module
is its machine-readable form and the ONE source of truth for lock order:

- ``tools/rtlint`` (the static analyzer, DESIGN.md §4d) imports
  ``GCS_LOCK_DAG`` / ``WORKER_LOCK_DAG`` and fails the build on any
  acquisition edge in ``gcs.py`` / ``worker.py`` outside them;
- ``RAY_TPU_LOCK_WATCHDOG=1`` wraps the live GCS locks in
  :class:`WatchdogLock`, which records actual acquisition stacks and
  asserts the SAME DAG at runtime — the chaos suite's dynamic oracle for
  the static rules (tests/test_gcs_locking.py).

An acquisition of ``inner`` while holding ``outer`` is legal iff
``inner`` is reachable from ``outer`` in the DAG (or ``outer == inner``:
RLock reentry cannot deadlock).  Leaf locks have empty successor sets —
nothing may be acquired under them.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Set, Tuple

# GcsServer lock domains (DESIGN.md §4c).  Canonical names are the
# attribute names, with ``cv`` folded into ``lock`` (the Condition wraps
# the same RLock).  ``task_conn_lock``/``ctl_conn_lock`` are per-
# WorkerState but are acquired by GCS threads holding the global lock
# (worker pushes happen inside the scheduler's critical section).
GCS_LOCK_DAG: Dict[str, Set[str]] = {
    "_persist_lock": {"lock"},   # snapshot writer: capture under the
    #                              global lock, write under persist only
    "lock": {"_waiter_lock", "_kv_lock", "_events_lock",
             "_peer_delete_lock", "task_conn_lock", "ctl_conn_lock",
             "raylet_conn_lock"},
    "_waiter_lock": set(),
    "_kv_lock": set(),
    "_events_lock": set(),
    "_dedup_lock": set(),
    "_peer_delete_lock": set(),
    "task_conn_lock": set(),
    "ctl_conn_lock": set(),
    # per-NodeState raylet lease-channel push lock: lease_grant /
    # lease_revoke pushes ride the scheduler's critical section exactly
    # like worker task pushes (bounded local-pipe sends, §4c)
    "raylet_conn_lock": set(),
}

# Leaf locks whose critical sections must stay O(dict op): calling a
# blocking primitive (socket send/recv, condition wait, sleep, file I/O)
# while holding one is an rtlint error.  ``_persist_lock`` is excluded
# by design — it IS the snapshot writer's file-I/O ordering lock — and
# the conn locks are excluded because pushes deliberately ride them
# (bounded local-pipe sends, documented in §4c).
GCS_NOBLOCK_LOCKS: Set[str] = {
    "_waiter_lock", "_kv_lock", "_events_lock", "_dedup_lock",
    "_peer_delete_lock"}

# Condition → underlying-lock aliases: ``with self.cv`` acquires
# ``lock``; ``cv.wait()`` releases it (so a wait is only "blocking while
# holding X" for the OTHER locks held at that point).
GCS_CV_ALIASES: Dict[str, str] = {"cv": "lock"}

# Worker (client-side) lock domains — see the declaration comments in
# worker.py for the ordering arguments.
WORKER_LOCK_DAG: Dict[str, Set[str]] = {
    "_release_lock": {"_submit_lock"},       # _drain_pending_pins
    # _drain_submits pop→send, and the send may first-dial the shared
    # oneway channel (rpc_oneway's lazy init) while serialized; the
    # raylet release route sits on the same rpc_oneway path (the
    # submit_batch kind never takes it, but the helper edge must be legal)
    "_submit_send_lock": {"_submit_lock", "_oneway_init_lock",
                          "_raylet_ref_lock"},
    "_submit_lock": set(),
    "_local_lock": set(),
    "_actor_chan_lock": set(),
    "_pull_lock": set(),
    "_owned_lock": set(),
    "_oneway_init_lock": set(),
    "_task_conn_lock": set(),
    # local-raylet release routing (one conn, lazily dialed + sent
    # under this lock; a bounded unix-pipe send by design)
    "_raylet_ref_lock": set(),
}

WORKER_NOBLOCK_LOCKS: Set[str] = {
    "_release_lock", "_submit_lock", "_local_lock", "_owned_lock",
    "_pull_lock"}

WORKER_CV_ALIASES: Dict[str, str] = {"_local_cv": "_local_lock"}

# Data-plane (data_plane.py) lock domains — all leaves, one per class:
# the server's serving-counter lock and the connection pool's table
# lock.  Neither is ever held across I/O or together with another lock
# (conn dial/close and frame streaming happen strictly outside them).
DATA_PLANE_LOCK_DAG: Dict[str, Set[str]] = {
    "_stats_lock": set(),
    "_lock": set(),
}

DATA_PLANE_CV_ALIASES: Dict[str, str] = {}

# Shm object store (shm_store.py): one lock guards the accounting
# tables (_sealed/_unsealed/_spilled/_used).  Spill file moves happen
# under it by design (eviction must be atomic with the accounting), so
# it is not a no-block leaf.
SHM_STORE_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

SHM_STORE_CV_ALIASES: Dict[str, str] = {}

# serve/llm paged KV cache (kv_cache.py): one leaf lock guards the
# allocator tables (free list, block tables, fills, refcounts).  Pool
# byte writes (scatter/write_token) are engine-loop-owned and happen
# OUTSIDE it by design — the lock protects placement, not payload.
LLM_KV_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

LLM_KV_CV_ALIASES: Dict[str, str] = {}

# serve/llm engine (engine.py): one leaf lock guards the cross-thread
# handoff state (inbox/attached queues, per-request stream registry).
# Scheduler and cache-payload state are engine-loop-owned (no lock);
# the cache's own leaf lock is never taken while holding this one.
LLM_ENGINE_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

LLM_ENGINE_CV_ALIASES: Dict[str, str] = {}

# Raylet (raylet.py, DESIGN.md §4i): ``_lock`` guards the local
# scheduler tables (queue, slots, done batch, ref nets, stats); worker
# pushes deliberately ride it through the per-slot conn locks (bounded
# local-pipe sends, the same §4c argument as GCS task pushes).
# ``_up_lock`` serializes upstream lease-channel sends and is a leaf:
# flushers collect under _lock, send under _up_lock, never nested.
RAYLET_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": {"conn_lock", "ctl_conn_lock"},
    "_up_lock": set(),
    "conn_lock": set(),
    "ctl_conn_lock": set(),
}

RAYLET_CV_ALIASES: Dict[str, str] = {}

# Fleet elasticity (elastic/, DESIGN.md §4j): the event subscriber's
# ``_cursor_lock`` is a no-block leaf guarding the feed cursor shared
# between the polling thread and inline poll_once callers; the RPC and
# subscriber callbacks run strictly outside it.  The manager itself is
# single-writer by design (transitions happen only on the fit thread)
# and holds no locks.
ELASTIC_LOCK_DAG: Dict[str, Set[str]] = {
    "_cursor_lock": set(),
}

ELASTIC_NOBLOCK_LOCKS: Set[str] = {"_cursor_lock"}

ELASTIC_CV_ALIASES: Dict[str, str] = {}

# GCS replication (replication.py, DESIGN.md §4l): both classes keep
# ONE no-block leaf lock.  The hub's ``_lock`` guards the WAL seq
# counter, the record buffer, and the standby adoption queue — GCS
# handler threads append under it in O(1) while holding GCS locks (the
# cross-domain edge mirrors lock -> _events_lock); every file write,
# fsync, and standby send happens on the single drain thread with no
# lock held.  The standby's ``_lock`` guards the applied tables +
# stream cursor; the stream recv and the promote file I/O run outside
# it (snapshot_state copies the tables out under it).
REPL_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
    "_promote_lock": {"_lock"},  # promote copies the tables under _lock
}

REPL_NOBLOCK_LOCKS: Set[str] = {"_lock"}

REPL_CV_ALIASES: Dict[str, str] = {}

# Fleet autopilot (elastic/autopilot.py, DESIGN.md §4n): one no-block
# leaf lock guards the bounded action history + per-(kind,outcome)
# counters shared between the ticking GCS monitor thread and
# ``autopilot_status`` RPC readers.  Every other piece of reflex state
# (rate window, per-node cooldown ledger, prewarm set) is single-writer
# — only the tick thread touches it — and actuator calls (which may
# take GCS locks) run with NO autopilot lock held.
AUTOPILOT_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

AUTOPILOT_NOBLOCK_LOCKS: Set[str] = {"_lock"}

AUTOPILOT_CV_ALIASES: Dict[str, str] = {}

# Metrics TSDB (util/tsdb.py, DESIGN.md §4k): one no-block leaf lock
# guards the series table, rings, and ingest counters.  Critical
# sections are O(dict/ring op); queries copy samples out under it and
# evaluate outside; the GCS calls ingest/query with NONE of its own
# locks held (the ingest hook in _h_kv_put runs after _kv_lock is
# released, the detector tick runs lock-free in the monitor loop).
TSDB_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

TSDB_NOBLOCK_LOCKS: Set[str] = {"_lock"}

TSDB_CV_ALIASES: Dict[str, str] = {}

# Profiling plane (util/profiler.py, DESIGN.md §4o): one no-block leaf
# lock guards BOTH halves — the sampler's folded-stack delta table
# (written by the sampling daemon, swapped out by the publisher) and
# the head ProfileStore's per-process window rings (written at receipt
# time, copied out by profile_query readers).  Critical sections are
# O(dict op); stack folding, JSON parsing, merging and diffing all run
# outside the leaf.
PROFILER_LOCK_DAG: Dict[str, Set[str]] = {
    "_lock": set(),
}

PROFILER_NOBLOCK_LOCKS: Set[str] = {"_lock"}

PROFILER_CV_ALIASES: Dict[str, str] = {}


def reachable(dag: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    """Transitive closure: lock → every lock legally acquirable under it."""
    closure: Dict[str, Set[str]] = {}
    for start in dag:
        seen: Set[str] = set()
        stack = list(dag.get(start, ()))
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            stack.extend(dag.get(n, ()))
        closure[start] = seen
    return closure


class LockOrderViolation(RuntimeError):
    """A thread acquired locks in an order outside the documented DAG."""


class WatchdogState:
    """Shared per-server watchdog bookkeeping (one per wrapped GcsServer)."""

    def __init__(self, dag: Dict[str, Set[str]]):
        self.dag = dag
        self.reach = reachable(dag)
        self._tls = threading.local()
        self._mu = threading.Lock()
        # (outer, inner) acquisition edges actually observed at runtime
        self.edges: Set[Tuple[str, str]] = set()
        # lock name → stack of the most recent acquisition (diagnostics)
        self.last_stacks: Dict[str, List[str]] = {}
        self.violations: List[str] = []

    def held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquire(self, name: str) -> None:
        """Validate ``name`` against every lock this thread holds; raise
        on a DAG violation (recording both stacks first)."""
        held = self.held()
        if name in held:
            return  # RLock reentry: cannot deadlock, records no edge
        bad = [h for h in held if name not in self.reach.get(h, set())]
        stack = traceback.format_stack()[:-2]
        with self._mu:
            for h in held:
                self.edges.add((h, name))
            self.last_stacks[name] = stack
            if bad:
                prior = self.last_stacks.get(bad[0], [])
                msg = (f"lock order violation: acquiring {name!r} while "
                       f"holding {held!r} (edge {bad[0]!r} -> {name!r} is "
                       f"outside the documented DAG)\n--- acquiring "
                       f"thread stack ---\n{''.join(stack)}--- last "
                       f"{bad[0]!r} acquisition ---\n{''.join(prior)}")
                self.violations.append(msg)
        if bad:
            raise LockOrderViolation(msg)

    def push(self, name: str) -> None:
        self.held().append(name)

    def pop(self, name: str) -> None:
        held = self.held()
        # release order may differ from acquire order (with-block nesting
        # guarantees LIFO, but .release() forms need not) — remove the
        # innermost matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def pop_all(self, name: str) -> int:
        """Remove every entry for ``name`` (Condition._release_save on an
        RLock releases all recursion levels at once)."""
        held = self.held()
        n = len(held)
        held[:] = [h for h in held if h != name]
        return n - len(held)


class WatchdogLock:
    """Wrap a Lock/RLock: assert DAG order on acquire, track held state.

    Forwards ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` so
    ``threading.Condition`` (cv.wait) keeps working on a wrapped RLock —
    a wait fully releases the lock (held-state popped) and restores it
    on wake (pushed back).
    """

    def __init__(self, inner, name: str, state: WatchdogState):
        self._inner = inner
        self.name = name
        self._state = state

    # Contended acquires above this land in the flight recorder: a
    # post-mortem ring then shows WHICH lock the process was starving
    # on in its final seconds (DESIGN.md §4h).
    SLOW_WAIT_S = 0.05

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._state.on_acquire(self.name)
        import time as _time
        # fold this thread under a synthetic ``waiting:<lock>`` frame in
        # the sampling profiler for the duration of the inner acquire —
        # lock contention then shows up in flames (DESIGN.md §4o)
        from ray_tpu.util import profiler as _profiler
        _profiler.note_lock_wait(self.name)
        t0 = _time.monotonic()
        try:
            got = self._inner.acquire(blocking, timeout)
        finally:
            _profiler.clear_lock_wait()
        waited = _time.monotonic() - t0
        if waited > self.SLOW_WAIT_S:
            from ray_tpu._private import flight_recorder
            if flight_recorder.enabled():
                flight_recorder.record(
                    "lockwait", f"{self.name} {waited * 1e3:.1f}ms")
        if got:
            self._state.push(self.name)
        return got

    def release(self) -> None:
        self._state.pop(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # --- threading.Condition integration -------------------------------
    def _release_save(self):
        n = self._state.pop_all(self.name)
        return (self._inner._release_save(), n)

    def _acquire_restore(self, saved) -> None:
        inner_state, n = saved
        self._inner._acquire_restore(inner_state)
        for _ in range(n):
            self._state.push(self.name)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def watchdog_enabled() -> bool:
    return os.environ.get("RAY_TPU_LOCK_WATCHDOG") == "1"


def wrap_gcs_locks(srv) -> WatchdogState:
    """Wrap a GcsServer's lock domains in watchdog locks (call right
    after the locks are created, BEFORE any server thread starts).  The
    Condition is rebuilt around the wrapped global lock so cv.wait
    releases/restores through the watchdog."""
    state = WatchdogState(GCS_LOCK_DAG)
    srv.lock = WatchdogLock(srv.lock, "lock", state)
    srv.cv = threading.Condition(srv.lock)
    for attr in ("_waiter_lock", "_kv_lock", "_events_lock",
                 "_dedup_lock", "_persist_lock", "_peer_delete_lock"):
        setattr(srv, attr, WatchdogLock(getattr(srv, attr),
                                        attr, state))
    srv._lock_watchdog = state
    return state


# ======================================================================
# Blocking-flow policy (DESIGN.md §4p) — the machine-readable side of
# tools/rtlint's ``blocking`` pass, mirroring how the lock DAGs above
# back the ``locks`` pass.  Three tables:
#
# - ``REACTOR_SAFE``: functions the item-1 reactor will call inline on
#   the event loop.  rtlint proves each is TRANSITIVELY non-blocking
#   over the whole in-repo call graph (rule ``block-reactor``) — seeded
#   with the wire codec, frame parse, and the shm ``_sealed``-table
#   read paths, and grown as handlers are made reactor-ready.
# - ``BLOCK_BOUNDS``: every site wrapped in :func:`bounded_block`
#   declares its worst-case bound (seconds) here.  rtlint asserts the
#   call sites and this table agree exactly (``block-bound-undeclared``
#   / ``block-bound-dead``), and the runtime oracle below asserts the
#   declared bound actually holds under the chaos suite.
# - the per-context *allowed blocking classes* live in
#   ``tools/rtlint/blocking.py`` next to the context list (they
#   parameterize the analysis, not the runtime).

# Dotted as ``module.func`` / ``module.Class.method`` relative to
# ray_tpu/_private (the reactor core lives there).
REACTOR_SAFE: Set[str] = {
    # wire codec + frame parse: encode/decode must run inline on the
    # reactor between readiness callbacks
    "wire.rtmsg_dumps",
    "wire.rtmsg_loads",
    "wire.encode_frame",
    "wire.decode_frame",
    "wire.decode_frame_ex",
    "wire.bulk_pack_header",
    "wire.bulk_unpack_header",
    "wire.negotiate_version",
    # shm ``_sealed``-table read paths: O(dict op) under leaf locks,
    # safe to answer from the loop (get_meta/peek fast path)
    "shm_store.ShmObjectStore.location",
    "shm_store.ShmObjectStore.touch",
    "shm_store.ShmObjectStore.stats",
    "shm_store.ShmObjectStore.exists_in_shm",
}

# site name -> worst-case block duration in seconds.  A site's bound is
# the DECLARED contract: the static pass pins each ``bounded_block``
# call to exactly one row here, and ``RAY_TPU_BLOCK_WATCHDOG=1``
# raises :class:`BlockBoundViolation` when a wrapped site overruns
# ``bound * RAY_TPU_BLOCK_WATCHDOG_SLACK``.  Keep bounds honest-worst-
# case (timeout argument + scheduling slop), not aspirational.
BLOCK_BOUNDS: Dict[str, float] = {
    # protocol.tunnel_connect: bounded handshake poll before the first
    # recv (proxy answers immediately; 30s covers a GC-pausing head)
    "protocol.tunnel_connect.handshake": 30.0,
    # gcs._dedup_begin: winner-completion wait for a duplicate two-way
    # mutation (ev.wait(30.0) literal)
    "gcs.dedup_wait": 30.0,
    # raylet._reconnect_upstream: one jittered backoff sleep
    # (backoff_delays cap=0.5 base=0.05; 1s absorbs jitter + scheduler
    # lag)
    "raylet.reconnect_backoff": 1.0,
    # raylet._done_flush_loop: batch-coalescing tick (wait(1.0) literal)
    "raylet.done_flush_tick": 1.0,
    # replication hub ticker: _event.wait(hb_period); dynamic bound
    # passed at the site, this row is the config-default ceiling
    "repl.hub_tick": 60.0,
    # standby stream poll: conn.poll(gcs_standby_timeout_s) — a poll
    # overrun means heartbeats stopped AND the poll itself wedged
    "repl.stream_poll": 60.0,
}


class BlockBoundViolation(RuntimeError):
    """A statically-declared-bounded blocking site overran its bound."""


def block_watchdog_enabled() -> bool:
    return os.environ.get("RAY_TPU_BLOCK_WATCHDOG") == "1"


def _block_slack() -> float:
    try:
        return float(os.environ.get("RAY_TPU_BLOCK_WATCHDOG_SLACK",
                                    "1.5"))
    except ValueError:
        return 1.5


# site -> [count, total_s, max_s]; guarded by: _BLOCK_STATS_LOCK
_BLOCK_STATS: Dict[str, List[float]] = {}
_BLOCK_STATS_LOCK = threading.Lock()


def block_stats() -> Dict[str, Tuple[int, float, float]]:
    """{site: (count, total_s, max_s)} observed since the last reset."""
    with _BLOCK_STATS_LOCK:
        return {k: (int(v[0]), v[1], v[2])
                for k, v in _BLOCK_STATS.items()}


def reset_block_stats() -> None:
    with _BLOCK_STATS_LOCK:
        _BLOCK_STATS.clear()


# ======================================================================
# XLA hygiene policy (DESIGN.md §4q) — the machine-readable side of
# tools/rtlint's ``jaxlint`` passes, and the declared contract the
# ``RAY_TPU_XLA_WATCHDOG=1`` runtime oracle (xla_watchdog.py) enforces.
# Same identity discipline as REACTOR_SAFE / BLOCK_BOUNDS above: the
# static passes parse THESE tables, the runtime oracle imports them,
# so neither can drift.

# Step paths: the compute-plane functions that make up a steady-state
# step — the train step body, the LLM prefill/decode programs and the
# engine's batching step, and the decomposed-collective ring bodies.
# Quals are ``module:qualname`` over the jaxlint call-graph scope
# (module key = file stem, nested defs dotted — same scheme as the
# blocking pass).  jaxlint proves each is transitively free of host
# syncs (``host-sync``) and scans everything reachable from them for
# retrace hazards (``retrace-*``).
STEP_PATHS: Set[str] = {
    # the one-jit distributed train step (forward+backward+optimizer)
    "spmd:build_train_program._step",
    # LLM serving programs (bucketed jits) + the engine batching step
    "gpt2:forward_prefill",
    "gpt2:forward_decode",
    "llama:forward_prefill",
    "llama:forward_decode",
    "engine:LLMEngine.step",
    # decomposed collective-matmul rings + the KV ring (§4m): a host
    # sync inside a ring body would serialize the whole ring
    "collective_matmul:all_gather_matmul",
    "collective_matmul:matmul_reduce_scatter",
    "ring_attention:ring_attention",
}

# Donating callables: bound name of a ``jax.jit(..., donate_argnums=)``
# result -> the argnums that are ALWAYS donated.  jaxlint checks the
# jit sites against this map both directions (``donate-undeclared`` /
# ``donate-dead``), diffs literal donate_argnums against it
# (``donate-drift``), and flags any read of a donated binding after a
# call to the named callable (``donate-use-after``).  ``step_fn``
# donates the whole TrainState (argnum 0) — params AND both Adam
# moments alias their outputs; the optional ``donate_batch`` argnum is
# deliberately NOT declared (callers that enable it feed fresh batches
# and the static rule covers the unconditional donation only).
DONATED: Dict[str, Tuple[int, ...]] = {
    "step_fn": (0,),
}

# compile_budget site -> declared steady-state compile ceiling (count
# of distinct XLA programs one region owner may build).  The runtime
# oracle raises :class:`XlaHygieneViolation` (xla_watchdog.py) when a
# site's owner exceeds ``budget + RAY_TPU_XLA_WATCHDOG_WARMUP``;
# jaxlint pins each ``compile_budget("<site>")`` call to exactly one
# row here (``compile-budget-undeclared`` / ``compile-budget-dead``).
# Keep ceilings honest: the bucket-table length for the bucketed LLM
# programs (a site override passes the live ``len(buckets)``), one
# program for the train step.
COMPILE_BUDGETS: Dict[str, int] = {
    # spmd.build_train_program: one program per SpmdProgram, ever —
    # shapes are pinned by the batch sharding, a second compile in
    # steady state means a retrace hazard escaped jaxlint
    "train.step": 1,
    # model_runner: one program per declared length/batch bucket
    # (site override passes len(cfg.prefill_len_buckets) /
    # len(cfg.decode_batch_buckets); these rows are the config-default
    # ceilings)
    "llm.prefill": 6,
    "llm.decode": 5,
}


class bounded_block:
    """Context manager wrapping one declared-bounded blocking site.

    ``with lw.bounded_block("gcs.dedup_wait"): ev.wait(30.0)``

    Zero-cost no-op unless ``RAY_TPU_BLOCK_WATCHDOG=1``.  When enabled:
    folds the blocked thread under a synthetic ``waiting:block:<site>``
    frame in the sampling profiler (same namespace as lock waits,
    DESIGN.md §4o), records the actual duration, and raises
    :class:`BlockBoundViolation` on exit if the site overran its
    declared bound times the slack factor.  ``bound=`` overrides the
    table's default for sites whose timeout is config-driven; the table
    row is still mandatory (it is the declared ceiling).
    """

    __slots__ = ("site", "bound", "_t0", "_armed")

    def __init__(self, site: str, bound: float = None):
        self.site = site
        self.bound = bound
        self._armed = block_watchdog_enabled()
        self._t0 = 0.0

    def __enter__(self):
        if not self._armed:
            return self
        if self.site not in BLOCK_BOUNDS:
            raise BlockBoundViolation(
                f"blocking site {self.site!r} is not declared in "
                f"lock_watchdog.BLOCK_BOUNDS (rtlint: "
                f"block-bound-undeclared)")
        import time as _time
        from ray_tpu.util import profiler as _profiler
        _profiler.note_lock_wait(f"block:{self.site}")
        self._t0 = _time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if not self._armed:
            return False
        import time as _time
        from ray_tpu.util import profiler as _profiler
        waited = _time.monotonic() - self._t0
        _profiler.clear_lock_wait()
        with _BLOCK_STATS_LOCK:
            st = _BLOCK_STATS.setdefault(self.site, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += waited
            st[2] = max(st[2], waited)
        eff = BLOCK_BOUNDS[self.site] if self.bound is None \
            else float(self.bound)
        if waited > eff * _block_slack() and exc_type is None:
            from ray_tpu._private import flight_recorder
            if flight_recorder.enabled():
                flight_recorder.record(
                    "blockwait",
                    f"{self.site} {waited:.3f}s > bound {eff:.3f}s")
            raise BlockBoundViolation(
                f"declared-bounded site {self.site!r} blocked for "
                f"{waited:.3f}s, over its declared bound {eff:.3f}s "
                f"(x{_block_slack()} slack)")
        return False
