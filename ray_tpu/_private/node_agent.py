"""NodeAgent: join this host to a remote head as a worker node.

Reference analog: the raylet's process-management half (SURVEY.md §2.1).
The agent dials the head's client-proxy port (per-session HMAC auth via
RTPU_AUTH_KEY), registers a node with this host's resources, and
maintains a pool of worker processes.  Against a head that speaks
``wire.PROTO_RAYLET`` it promotes itself into a **raylet**
(``_private/raylet.py``, DESIGN.md §4i): a per-node local scheduler that
claims worker leases in bulk, dispatches intra-node tasks without a head
round-trip, nets owner-local refcount releases, and uses ONE keepalive
channel (the lease channel's heartbeat) for node liveness.  Against an
older head — or with ``raylet_enabled=0`` — it falls back byte-identical
to the legacy mode: workers attach their task conns straight to the GCS
through the tunnel and a dedicated ``agent_attach`` conn carries
liveness.  Actors in both modes listen on ephemeral TCP ports and
advertise ``tcp://<this-host>:<port>`` addresses; callers dial them
directly, or relay through the head's client proxy when sibling hosts
aren't mutually reachable.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private import protocol, rtlog

logger = rtlog.get("node-agent")


class NodeAgent:
    def __init__(self, head_host: str, head_port: int, *,
                 num_cpus: Optional[int] = None,
                 num_tpus: float = 0,
                 labels: Optional[Dict[str, str]] = None,
                 resources: Optional[Dict[str, float]] = None):
        self.head = (head_host, head_port)
        self.num_workers = int(num_cpus or os.cpu_count() or 1)
        self.num_tpus = float(num_tpus or 0)
        res = dict(resources or {})
        res["CPU"] = float(self.num_workers)
        if self.num_tpus:
            # this host's chips: served by ONE device-holding worker (the
            # same one-jax-process-per-host rule as head-local TPU workers)
            res["TPU"] = self.num_tpus
        all_labels = {"agent": "1", **(labels or {})}
        self._conn = protocol.tunnel_connect(*self.head, "gcs")
        try:
            self._chan = protocol.RpcChannel(self._conn, negotiate=True)
            # P2P object plane (reference: ObjectManager node↔node
            # transfer): large objects produced on this host spool
            # locally and are served directly to sibling hosts; the head
            # is only the fallback relay.
            import tempfile
            from ray_tpu._private import wire
            from ray_tpu._private.data_plane import DataPlaneServer
            self._spool_dir = tempfile.mkdtemp(prefix="rtpu_spool_")
            self._data_plane = DataPlaneServer(
                self._spool_dir, advertise_host=self._advertise_host())
            # data_proto advertises this host's data-plane wire ceiling
            # so the head's pooled pull/delete conns skip the per-conn
            # hello (an old head ignores the extra field)
            node_info = dict(resources=res, labels=all_labels, remote=True,
                             data_addr=self._data_plane.advertise_addr,
                             data_proto=wire.DATA_PROTO_MAX)
            resp = self._chan.call("add_node", **node_info)
            self.node_id = resp["node_id"]
            self._procs: List[subprocess.Popen] = []
            self._extra_procs: List[subprocess.Popen] = []
            self._stop = threading.Event()
            self._draining = False
            self.raylet = None
            from ray_tpu._private.config import GLOBAL_CONFIG
            if GLOBAL_CONFIG.raylet_enabled \
                    and self._chan.version >= wire.PROTO_RAYLET:
                # Promote to a raylet (DESIGN.md §4i): the add_node conn
                # becomes the lease channel — grants down, batched
                # results/refcount reconciliation/heartbeats up.  It is
                # ALSO the node's one liveness path (keepalive dedup:
                # no separate agent_attach conn, no _liveness_watch).
                from ray_tpu._private import flight_recorder, raylet
                sess = resp.get("session")
                if sess:
                    # same-host rings land in the head session's tmpfs
                    # dir (flight_dir_for keys on the path NAME) so
                    # `ray_tpu debug dump` collects them; the no-/dev/shm
                    # fallback then writes under OUR spool dir, not "/"
                    flight_recorder.maybe_install(
                        os.path.join(self._spool_dir, str(sess)),
                        "raylet")
                from ray_tpu.util import profiler as profiler_mod
                profiler_mod.maybe_install("raylet")
                self.raylet = raylet.Raylet(
                    self.head, self.node_id, node_info,
                    sock_dir=self._spool_dir,
                    spawn_cb=self._spawn_extra,
                    on_lost=self.stop,
                    upstream_conn=self._conn,
                    upstream_version=self._chan.version)
            else:
                # legacy path (old head / raylets disabled): dedicate
                # this connection to liveness — the head removes the
                # node when it drops (kill -9 / host crash / partition)
                self._chan.send_oneway("agent_attach", node_id=self.node_id)
                # watch the liveness conn from OUR side too: a dropped
                # TCP conn makes the head remove the node; without this
                # the agent would keep an orphaned pool running
                threading.Thread(target=self._liveness_watch, daemon=True,
                                 name="agent-liveness").start()
            # per-node OOM killer (reference: MemoryMonitor runs inside
            # each raylet): THIS host's pressure, THIS host's pids.
            # Victim policy stays with the head (pick_oom_victim RPC)
            # which pre-marks the task so the death surfaces as a
            # retriable OutOfMemoryError.
            threading.Thread(target=self._memory_watch, daemon=True,
                             name="agent-memory-monitor").start()
        except BaseException:
            # a failed join (version fence, head rejecting add_node,
            # agent_attach send failing) returns no agent: close the
            # dialed conn, stop the already-listening data plane, and
            # drop the spool dir — a retry loop around NodeAgent() must
            # not accrete a listener + tempdir per attempt
            try:
                self._conn.close()
            except OSError:
                pass
            dp = getattr(self, "_data_plane", None)
            if dp is not None:
                dp.stop()
            sd = getattr(self, "_spool_dir", None)
            if sd is not None:
                import shutil
                shutil.rmtree(sd, ignore_errors=True)
            raise
        logger.info("joined head %s:%s as node %s (%d workers)",
                    head_host, head_port, self.node_id[:8], self.num_workers)

    def _memory_watch(self) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.memory_monitor import node_memory_usage
        while not self._stop.is_set():
            self._stop.wait(max(GLOBAL_CONFIG.memory_monitor_interval_s, 0.1))
            threshold = GLOBAL_CONFIG.memory_usage_threshold
            if threshold >= 1.0 or threshold <= 0:
                continue
            used, total = node_memory_usage()
            if not total or used / total < threshold:
                continue
            # catch broadly: RpcChannel.call re-raises arbitrary
            # deserialized server-side exceptions, and this daemon thread
            # dying would silently strip the node of OOM protection
            ch = None
            try:
                ch = protocol.RpcChannel(
                    protocol.tunnel_connect(*self.head, "gcs"),
                    negotiate=True)
                resp = ch.call("pick_oom_victim", node_id=self.node_id,
                               frac=used / total)
                pid = resp.get("pid")
                # only kill pids of processes THIS agent spawned — the
                # head's view may be stale, and a recycled pid must never
                # be signaled
                for p in self._procs:
                    if pid and p.pid == pid and p.poll() is None:
                        logger.warning(
                            "memory %.0f%% >= %.0f%%: OOM-killing worker "
                            "pid=%d", 100 * used / total,
                            100 * threshold, pid)
                        confirmed = False
                        try:
                            # confirm first: the head marks the task as
                            # OOM-killed only when the kill actually
                            # happens (a skipped kill must not mislabel a
                            # later unrelated death), and only if the
                            # picked task is STILL the one running
                            confirmed = ch.call(
                                "confirm_oom_kill", pid=pid,
                                worker_id=resp.get("worker_id"),
                                task_id=resp.get("task_id")).get("ok")
                        except Exception:  # noqa: BLE001
                            pass
                        if confirmed:
                            try:
                                p.kill()
                            except OSError:
                                pass
                        break
            except Exception:  # noqa: BLE001 - keep the monitor alive
                logger.exception("memory watch pass failed")
            finally:
                if ch is not None:
                    try:
                        ch.close()
                    except OSError:
                        pass

    def _liveness_watch(self) -> None:
        try:
            self._conn.recv()  # the head never sends; EOF = detached
        except (EOFError, OSError):
            pass
        if not self._stop.is_set():
            logger.error("lost connection to head; shutting down pool")
            self.stop()

    # -- worker pool ---------------------------------------------------------
    def _advertise_host(self) -> str:
        """This host's address as seen on the route to the head — what
        actor TCP listeners advertise to cross-host callers."""
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(self.head)  # UDP connect: no packets, just routing
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"
        finally:
            s.close()

    def _spawn(self, tpu: bool = False) -> subprocess.Popen:
        env = dict(os.environ)
        env["RTPU_PROXY_ADDR"] = f"{self.head[0]}:{self.head[1]}"
        env["RTPU_NODE_ID"] = self.node_id
        env["RTPU_ADVERTISE_HOST"] = self._advertise_host()
        env["RTPU_SPOOL_DIR"] = self._spool_dir
        env["RTPU_DATA_ADDR"] = self._data_plane.advertise_addr
        if self.raylet is not None:
            # workers attach task/ctl conns to the LOCAL raylet socket
            # (and route release oneways there for netting) instead of
            # tunneling every frame to the head
            env["RTPU_RAYLET_SOCK"] = self.raylet.sock_path
        if tpu:
            # device-holding worker: jax initializes the real platform
            env["RTPU_TPU_WORKER"] = "1"
            env.pop("JAX_PLATFORMS", None)
        else:
            # CPU workers must not claim the chip or pay the tunnel's
            # sitecustomize import (shared scrub, ray_tpu._private.axon_env)
            from ray_tpu._private.axon_env import scrub_tpu_tunnel
            scrub_tpu_tunnel(env)
        env.pop("RTPU_SESSION_DIR", None)
        sink = None if os.environ.get("RTPU_AGENT_WORKER_LOG") \
            else subprocess.DEVNULL  # debug: inherit stderr when set
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env, stdout=sink, stderr=sink)

    def _spawn_extra(self) -> None:
        """Raylet callback: fork one replacement worker (pool blocked in
        get() with leased work queued).  Not respawned on exit — the
        base pool slots are the durable capacity."""
        if self._stop.is_set():
            return
        self._extra_procs.append(self._spawn())

    def run(self) -> None:
        """Maintain the pool until stopped; respawn dead workers with
        exponential backoff (a head outage or startup import error must
        not become a silent fork loop)."""
        self._tpu_slots = 1 if self.num_tpus else 0
        self._procs = [self._spawn(tpu=i < self._tpu_slots)
                       for i in range(self._tpu_slots + self.num_workers)]
        spawn_times = [time.monotonic()] * len(self._procs)
        backoff = [1.0] * len(self._procs)
        while not self._stop.is_set():
            time.sleep(0.5)
            # reap finished replacement workers (no respawn)
            self._extra_procs = [p for p in self._extra_procs
                                 if p.poll() is None]
            for i, p in enumerate(self._procs):
                if p.poll() is None or self._stop.is_set():
                    continue
                lived = time.monotonic() - spawn_times[i]
                if lived < 5.0:
                    backoff[i] = min(backoff[i] * 2, 30.0)
                    logger.warning(
                        "worker slot %d exited after %.1fs (rc=%s); "
                        "respawning in %.0fs", i, lived, p.returncode,
                        backoff[i])
                    self._stop.wait(backoff[i])
                else:
                    backoff[i] = 1.0
                if self._stop.is_set():
                    break  # stop() during the backoff wait: no respawn
                # slot i keeps its role: a dead TPU worker must come back
                # TPU-capable or TPU tasks pinned to this node hang forever
                self._procs[i] = self._spawn(tpu=i < self._tpu_slots)
                spawn_times[i] = time.monotonic()

    def drain(self, reason: str = "preemption",
              deadline_s: float = 0.0) -> None:
        """Provider-initiated preemption warning (DESIGN.md §4j): report
        ``node_draining`` upstream so the head stops placing work here
        and the elasticity manager can re-mesh the training group away,
        then stop after the warning window.  Idempotent; SIGTERM with
        ``RTPU_DRAIN_GRACE_S`` set routes here (the Kubernetes
        terminationGracePeriod model: TERM = warning, KILL = deadline)."""
        if self._draining or self._stop.is_set():
            return
        self._draining = True
        logger.warning("draining node %s (%s): stopping in %.0fs",
                       self.node_id[:8], reason, deadline_s)
        ch = None
        try:  # fresh conn: the add_node conn belongs to liveness/raylet
            ch = protocol.RpcChannel(
                protocol.tunnel_connect(*self.head, "gcs"),
                negotiate=True)
            ch.call("node_draining", node_id=self.node_id,
                    reason=reason, deadline_s=deadline_s)
        except Exception:  # noqa: BLE001 - head gone: just stop on time
            logger.exception("node_draining report failed")
        finally:
            if ch is not None:
                try:
                    ch.close()
                except OSError:
                    pass
        if deadline_s > 0:
            t = threading.Timer(deadline_s, self.stop)
            t.daemon = True
            t.name = "agent-drain-deadline"
            t.start()
        else:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self.raylet is not None:
            # clean leave: the raylet flushes its unsettled results and
            # netted releases, RETURNS unstarted leases, and detaches —
            # the head reclaims nothing by death-detection and removes
            # the node itself (no remove_node RPC needed)
            self.raylet.stop()
        for p in self._procs + self._extra_procs:
            try:
                p.terminate()
            except OSError:
                pass
        if self.raylet is None:
            ch = None
            try:  # fresh conn: the attach conn is dedicated to liveness
                ch = protocol.RpcChannel(
                    protocol.tunnel_connect(*self.head, "gcs"),
                    negotiate=True)
                ch.call("remove_node", node_id=self.node_id)
            except Exception:  # noqa: BLE001 - head may already be gone
                pass
            finally:
                if ch is not None:
                    ch.close()
        try:
            self._conn.close()
        except OSError:
            pass
        self._data_plane.stop()
        logger.info("data plane served %d objects / %d bytes over %d conns",
                    self._data_plane.objects_served,
                    self._data_plane.bytes_served,
                    self._data_plane.conns_accepted)
        import shutil
        shutil.rmtree(self._spool_dir, ignore_errors=True)


def _detect_tpu_env() -> Dict[str, str]:
    """TPU topology hints from the ambient environment (GKE TPU node pools
    export TPU_WORKER_ID/TPU_WORKER_HOSTNAMES/TPU_ACCELERATOR_TYPE; the
    deploy/k8s manifests additionally pass RTPU_* explicitly).

    ``ici_domain`` must be unique *per slice* ("<topology>/<slice-id>",
    parallel/topology.py convention), not per accelerator type — two
    distinct v5litepod-8 slices share no ICI, and collapsing them into one
    domain would let STRICT_PACK span disconnected slices.  The slice
    identity comes from TPU_WORKER_HOSTNAMES (identical on every host of a
    slice, distinct across slices)."""
    import hashlib

    labels = {}
    acc = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5litepod-8"
    if acc:
        labels["tpu_accelerator"] = acc
        # Multi-host slices: TPU_WORKER_HOSTNAMES is identical on every
        # host of the slice and distinct across slices.  Single-host node
        # pools don't get it — there each HOST is its own ICI domain, so
        # fall back to this host's name (never a shared constant: two
        # single-host nodes of the same type share no ICI).
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        ident = hosts or socket.gethostname()
        slice_id = hashlib.sha1(ident.encode()).hexdigest()[:8]
        labels.setdefault("ici_domain", f"{acc}/{slice_id}")
    wid = os.environ.get("TPU_WORKER_ID")
    if wid is not None:
        labels["slice_host"] = str(wid)
    return labels


def parse_labels(spec: str) -> Dict[str, str]:
    """``k=v,k2=v2`` → dict (CLI --labels format).  A bare item without
    '=' is rejected: a typo'd label (e.g. ``ici_domain`` for
    ``ici_domain=...``) must fail fast, not register an empty-string label
    that label-equality placement would silently group on."""
    out: Dict[str, str] = {}
    for item in (spec or "").split(","):
        if not item:
            continue
        k, sep, v = item.partition("=")
        if not sep or not k.strip():
            raise ValueError(f"malformed label {item!r}: expected k=v")
        out[k.strip()] = v.strip()
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="ray_tpu node-agent")
    ap.add_argument("--address", required=True, help="head HOST:PORT "
                    "(the head's --client-server-port)")
    ap.add_argument("--num-cpus", type=int, default=0)
    ap.add_argument("--num-tpus", type=float,
                    default=float(os.environ.get("RTPU_NUM_TPUS", 0) or 0),
                    help="TPU chips on this host (default: $RTPU_NUM_TPUS); "
                         "served by one device-holding worker")
    ap.add_argument("--labels", default=os.environ.get("RTPU_NODE_LABELS", ""),
                    help="node labels k=v,k2=v2 (default: $RTPU_NODE_LABELS); "
                         "merged over GKE TPU metadata autodetection")
    args = ap.parse_args(argv)
    host, _, port = args.address.partition(":")
    protocol.set_authkey_from_env()
    rtlog.setup("node-agent", None)
    labels = {**_detect_tpu_env(), **parse_labels(args.labels)}
    agent = NodeAgent(host, int(port or 10001),
                      num_cpus=args.num_cpus or None,
                      num_tpus=args.num_tpus,
                      labels=labels or None)
    def _on_term(*_):
        # TERM is the provider's preemption warning when a grace window
        # is configured (Kubernetes terminationGracePeriod model): the
        # agent reports node_draining and keeps serving until the
        # deadline.  No grace -> the old immediate clean leave.  The RPC
        # runs off-thread: signal handlers must not block on sockets.
        grace = float(os.environ.get("RTPU_DRAIN_GRACE_S", "0") or 0)
        if grace > 0:
            threading.Thread(
                target=agent.drain,
                kwargs=dict(reason="sigterm", deadline_s=grace),
                daemon=True, name="agent-drain").start()
        else:
            agent.stop()

    signal.signal(signal.SIGTERM, _on_term)
    try:
        agent.run()
    except KeyboardInterrupt:
        agent.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
