"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Reference: ``python/ray/_private/runtime_env/`` (SURVEY.md §2.3) — the
driver uploads ``working_dir``/``py_modules`` into the GCS KV
(content-addressed zips); workers download+extract into a session cache,
chdir into the working dir and extend ``sys.path``, then undo after the
task (env application is per-task here since workers are pooled).

``pip`` isolation (reference: ``runtime_env={"pip": [...]}``) creates a
cached venv per requirement-set hash (``--system-site-packages`` so jax &
friends stay visible) and applies it per task by prefixing the venv's
site-packages on ``sys.path``; restore removes the path AND purges modules
imported from the venv, so the pooled worker stays clean.  Local
wheel/sdist paths are uploaded into the GCS KV at submit and materialized
on the executing host — installs run ``--no-index`` (zero-egress; index
requirements fail loudly).

``conda`` isolation (r3) creates/reuses a cached env per spec hash via the
first available mamba/micromamba/conda binary; ``container`` (r3) wraps
worker exec in podman/docker.  Both validate loudly as unsupported when no
binary exists on the host — which is the case in this image, so their tests
(tests/test_runtime_env_plugins.py) exercise them against in-tree fake
binaries; see PARITY.md for that caveat.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules", "pip", "conda",
                  "container", "config"}
_URI_PREFIX = "kv://runtime_env/"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_ZIP_BYTES = 64 * 1024 * 1024


def _conda_exe() -> Optional[str]:
    import shutil
    for name in ("mamba", "micromamba", "conda"):
        exe = shutil.which(name)
        if exe:
            return exe
    return None


def _container_exe() -> Optional[str]:
    import shutil
    for name in ("podman", "docker"):
        exe = shutil.which(name)
        if exe:
            return exe
    return None


def validate(runtime_env: Optional[dict]) -> None:
    if not runtime_env:
        return
    unknown = set(runtime_env) - SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; supported: "
            f"{sorted(SUPPORTED_KEYS)}")
    # graceful validated-unsupported (reference: plugin validation at
    # submission): fail at submit with a clear message, not in a worker
    if runtime_env.get("conda") and _conda_exe() is None:
        raise ValueError(
            "runtime_env['conda'] requires a conda/mamba/micromamba "
            "binary on PATH; none found on this host "
            "(validated-unsupported)")
    if runtime_env.get("container") and _container_exe() is None:
        raise ValueError(
            "runtime_env['container'] requires a podman or docker binary "
            "on PATH; none found on this host (validated-unsupported)")


# ---------------------------------------------------------------- packaging
def _zip_dir(path: Path) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for p in sorted(path.rglob("*")):
            if any(part in _EXCLUDE_DIRS for part in p.parts):
                continue
            if p.is_file():
                # fixed date_time → content-addressed hash is stable
                zi = zipfile.ZipInfo(str(p.relative_to(path)),
                                     date_time=(1980, 1, 1, 0, 0, 0))
                zi.external_attr = (p.stat().st_mode & 0xFFFF) << 16
                zf.writestr(zi, p.read_bytes())
    data = buf.getvalue()
    if len(data) > _MAX_ZIP_BYTES:
        raise ValueError(f"working_dir zip is {len(data)} bytes "
                         f"(limit {_MAX_ZIP_BYTES}); exclude large data")
    return data


def upload_dir(path: str, worker) -> str:
    """Zip + content-address + store in GCS KV; returns kv:// URI."""
    p = Path(path).resolve()
    if not p.is_dir():
        raise ValueError(f"runtime_env directory not found: {path}")
    data = _zip_dir(p)
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"runtime_env/{digest}"
    uri = _URI_PREFIX + digest
    if not worker.rpc("kv_get", key=key).get("value"):
        worker.rpc("kv_put", key=key, value=data)
    return uri


_WHL_PREFIX = "kvwhl://runtime_env/"


def upload_file(path: Path, worker) -> str:
    """Content-address one local file (wheel/sdist) into the KV; the URI
    keeps the original filename — pip parses wheel metadata from it.

    prepare() runs on EVERY submit, so repeats are memoized by
    (path, mtime, size) ON THE WORKER (memo dies with the cluster
    connection — a module-level memo would survive init/shutdown/init and
    skip the upload into a fresh, empty KV) and KV existence is probed
    with kv_keys (metadata only) — never by fetching the blob back just
    to test truthiness."""
    memo = getattr(worker, "_renv_upload_memo", None)
    if memo is None:
        memo = worker._renv_upload_memo = {}
    st = path.stat()
    memo_key = (str(path), st.st_mtime, st.st_size)
    uri = memo.get(memo_key)
    if uri is not None:
        return uri
    data = path.read_bytes()
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"runtime_env/{digest}"
    if not worker.rpc("kv_keys", prefix=key).get("keys"):
        worker.rpc("kv_put", key=key, value=data)
    uri = f"{_WHL_PREFIX}{digest}/{path.name}"
    memo[memo_key] = uri
    return uri


def prepare(runtime_env: Optional[dict], worker) -> Optional[dict]:
    """Driver-side: resolve local paths into uploaded URIs (at submit)."""
    if not runtime_env:
        return runtime_env
    validate(runtime_env)
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith(_URI_PREFIX):
        env["working_dir"] = upload_dir(wd, worker)
    mods = env.get("py_modules")
    if mods:
        env["py_modules"] = [
            m if str(m).startswith(_URI_PREFIX) else upload_dir(m, worker)
            for m in mods]
    pip = env.get("pip")
    if pip:
        if isinstance(pip, str):
            pip = [pip]
        resolved = []
        for req in pip:
            req = str(req)
            if req.startswith(_WHL_PREFIX):
                resolved.append(req)
            elif Path(req).expanduser().is_file():
                # local wheel/sdist: ship through the KV so any host's
                # worker can install it (zero-egress: no index fetches)
                resolved.append(upload_file(Path(req).expanduser().resolve(),
                                            worker))
            else:
                resolved.append(req)
        env["pip"] = sorted(resolved)
    return env


# --------------------------------------------------------------- worker side
def _env_cache_root(worker) -> Path:
    """Root for ALL per-host runtime-env caches (zips and venvs).

    Session dir when the worker has one; else a per-user tmp dir — a
    world-shared path would let another user pre-seed content-addressed
    entries, so the dir is created 0o700 and a pre-existing dir with the
    wrong owner/mode is rejected (mkdir with exist_ok succeeds silently
    on an attacker-owned path)."""
    if worker.session is not None:
        return Path(worker.session.path)
    import getpass
    import stat as stat_mod
    import tempfile
    root = Path(tempfile.gettempdir()) / f"rtpu_remote_{getpass.getuser()}"
    root.mkdir(mode=0o700, exist_ok=True)
    st = root.stat()
    if st.st_uid != os.getuid() or stat_mod.S_IMODE(st.st_mode) != 0o700:
        raise PermissionError(
            f"{root} exists with wrong owner/mode; refusing to use it "
            f"as the runtime_env cache")
    return root


def ensure_local(uri: str, worker) -> Path:
    """Fetch + extract a kv:// URI into the session cache; idempotent."""
    digest = uri[len(_URI_PREFIX):]
    cache = _env_cache_root(worker) / "runtime_env" / digest
    if cache.exists():
        return cache
    raw = worker.rpc("kv_get", key=f"runtime_env/{digest}").get("value")
    if raw is None:
        raise FileNotFoundError(f"runtime_env blob missing from KV: {uri}")
    tmp = cache.with_name(cache.name + f".tmp{os.getpid()}")
    tmp.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(raw)) as zf:
        zf.extractall(tmp)
    try:
        tmp.rename(cache)  # atomic publish; losers clean up
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return cache


def _venv_site_packages(venv_dir: Path) -> Path:
    cands = sorted(venv_dir.glob("lib/python*/site-packages"))
    if not cands:
        raise FileNotFoundError(f"no site-packages under {venv_dir}")
    return cands[0]


def ensure_pip_env(pip: List[str], worker) -> Path:
    """Create-or-reuse the venv for this requirement set; returns its
    site-packages dir.

    venv per sha256(requirements) under ``<cache>/runtime_env/venvs``
    (reference: per-job cached pip environments created by the runtime-env
    agent).  Creation runs under an flock so pooled workers racing on
    first use build it once; the venv uses --system-site-packages (jax and
    the baked-in stack stay importable) and installs with --no-index
    (zero-egress: local wheels via the KV; index requirements fail
    loudly)."""
    import fcntl
    import subprocess

    spec = sorted(str(r) for r in pip)
    digest = hashlib.sha256("\n".join(spec).encode()).hexdigest()[:16]
    venv_root = _env_cache_root(worker) / "runtime_env" / "venvs"
    venv_dir = venv_root / digest
    if venv_dir.exists():
        return _venv_site_packages(venv_dir)
    venv_root.mkdir(parents=True, exist_ok=True)
    lock_path = venv_root / f".{digest}.lock"
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        if venv_dir.exists():  # lost the race: winner built it
            return _venv_site_packages(venv_dir)
        # materialize KV wheels (filename preserved — pip reads wheel
        # metadata from it)
        wheel_dir = venv_root / f".{digest}.wheels"
        wheel_dir.mkdir(exist_ok=True)
        install_args = []
        for req in spec:
            if req.startswith(_WHL_PREFIX):
                blob_id, _, fname = req[len(_WHL_PREFIX):].partition("/")
                raw = worker.rpc("kv_get",
                                 key=f"runtime_env/{blob_id}").get("value")
                if raw is None:
                    raise FileNotFoundError(
                        f"runtime_env wheel missing from KV: {req}")
                wheel_path = wheel_dir / fname
                wheel_path.write_bytes(raw)
                install_args.append(str(wheel_path))
            else:
                install_args.append(req)
        tmp = venv_root / f".{digest}.tmp"
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        import venv as venv_mod
        venv_mod.create(tmp, system_site_packages=True, with_pip=True,
                        symlinks=True)
        proc = subprocess.run(
            [str(tmp / "bin" / "python"), "-m", "pip", "install",
             "--no-index", "--quiet", *install_args],
            capture_output=True, text=True)
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(wheel_dir, ignore_errors=True)
            raise RuntimeError(
                f"pip runtime_env install failed (--no-index; only local/"
                f"KV wheels resolve in this zero-egress build): "
                f"{proc.stderr[-800:]}")
        os.rename(tmp, venv_dir)  # atomic publish under the lock
        shutil.rmtree(wheel_dir, ignore_errors=True)
    return _venv_site_packages(venv_dir)


def _resolve_existing_conda_env(exe: str, name_or_prefix: str) -> Path:
    """Ray's string form names an EXISTING env (by name or prefix path)."""
    import json as json_mod
    import subprocess

    p = Path(name_or_prefix).expanduser()
    if os.sep in name_or_prefix or p.is_dir():
        if not p.is_dir():
            raise FileNotFoundError(
                f"conda env prefix does not exist: {name_or_prefix}")
        return p
    proc = subprocess.run([exe, "env", "list", "--json"],
                          capture_output=True, text=True)
    if proc.returncode == 0:
        try:
            for env_path in json_mod.loads(proc.stdout).get("envs", []):
                if Path(env_path).name == name_or_prefix:
                    return Path(env_path)
        except ValueError:
            pass
    raise FileNotFoundError(
        f"conda env {name_or_prefix!r} not found (conda env list)")


def ensure_conda_env(spec: Any, worker) -> Path:
    """Create-or-reuse a conda env for this spec; returns the env prefix.

    Spec forms (reference conda plugin semantics):
    - str: the NAME or PREFIX of an existing env — used as-is, never
      created;
    - list of package strings, or a dict in the environment.yml subset
      {"dependencies": [... , {"pip": [...]}], "channels": [...]}:
      created with the same cache discipline as pip (one env per
      sha256(canonical spec incl. channels) under
      ``<cache>/runtime_env/conda``, built once under an flock,
      atomically published via rename); nested pip deps install into the
      env's own python afterwards."""
    import fcntl
    import shutil
    import subprocess

    exe = _conda_exe()
    if exe is None:
        raise RuntimeError("no conda/mamba binary on PATH")
    if isinstance(spec, str):
        return _resolve_existing_conda_env(exe, spec)
    channels: List[str] = []
    pip_deps: List[str] = []
    if isinstance(spec, dict):
        channels = [str(c) for c in spec.get("channels", [])]
        deps = []
        for d in spec.get("dependencies", []):
            if isinstance(d, dict):
                pip_deps += [str(x) for x in d.get("pip", [])]
            else:
                deps.append(str(d))
    else:
        deps = [str(d) for d in spec]
    deps = sorted(deps)
    pip_deps = sorted(pip_deps)
    canonical = "\n".join(["C:" + c for c in channels] + deps +
                          ["P:" + p for p in pip_deps])
    digest = hashlib.sha256(canonical.encode()).hexdigest()[:16]
    root = _env_cache_root(worker) / "runtime_env" / "conda"
    env_dir = root / digest
    if env_dir.exists():
        return env_dir
    root.mkdir(parents=True, exist_ok=True)
    with open(root / f".{digest}.lock", "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        if env_dir.exists():
            return env_dir
        tmp = root / f".{digest}.tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        chan_flags = [f for c in channels for f in ("-c", c)]
        proc = subprocess.run(
            [exe, "create", "-y", "-p", str(tmp), *chan_flags, *deps],
            capture_output=True, text=True)
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"conda runtime_env create failed: {proc.stderr[-800:]}")
        if pip_deps:
            env_py = tmp / "bin" / "python"
            pip_cmd = [str(env_py) if env_py.exists() else sys.executable,
                       "-m", "pip", "install", "--no-index", *pip_deps]
            proc = subprocess.run(pip_cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    f"conda runtime_env pip section failed (--no-index; "
                    f"zero-egress build): {proc.stderr[-800:]}")
        os.rename(tmp, env_dir)  # atomic publish under the lock
    return env_dir


_CONTAINER_BOOTSTRAP = (
    "import pickle,sys,traceback\n"
    "fn,a,k=pickle.load(open('/rtpu_io/in.pkl','rb'))\n"
    "try:\n"
    "    out=(True,fn(*a,**k))\n"
    "except BaseException as e:\n"
    "    out=(False,e)\n"
    "pickle.dump(out,open('/rtpu_io/out.pkl','wb'))\n")


def run_in_container(container: Any, fn, args, kwargs, worker) -> Any:
    """Per-task exec prefix (reference: the container runtime-env plugin
    runs the worker inside the image).  The task body ships as a pickle
    through a bind-mounted scratch dir; the container runs a one-shot
    bootstrap and pickles back (ok, result | exception)."""
    import pickle
    import subprocess
    import tempfile

    import cloudpickle

    exe = _container_exe()
    if exe is None:
        raise RuntimeError("no podman/docker binary on PATH")
    if isinstance(container, str):
        image, run_options = container, []
    else:
        image = container["image"]
        run_options = [str(o) for o in container.get("run_options", [])]
    with tempfile.TemporaryDirectory(prefix="rtpu_ctr_") as td:
        (Path(td) / "in.pkl").write_bytes(
            cloudpickle.dumps((fn, args, kwargs)))
        cmd = [exe, "run", "--rm", "-v", f"{td}:/rtpu_io", *run_options,
               image, "python", "-c", _CONTAINER_BOOTSTRAP]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=float(os.environ.get(
                                  "RTPU_CONTAINER_TASK_TIMEOUT", 3600)))
        out_path = Path(td) / "out.pkl"
        if proc.returncode != 0 or not out_path.exists():
            raise RuntimeError(
                f"container task failed (rc={proc.returncode}): "
                f"{proc.stderr[-800:]}")
        ok, payload = pickle.loads(out_path.read_bytes())
    if ok:
        return payload
    raise payload


def apply(runtime_env: Optional[dict], worker) -> Dict[str, Any]:
    """Apply working_dir/py_modules/env_vars; returns restore state.

    Exception-safe: a failure mid-application (missing KV blob, corrupt
    zip) restores whatever was already applied before re-raising, so the
    pooled worker process is left clean for the next task."""
    saved: Dict[str, Any] = {"env": {}, "cwd": None, "sys_path": [],
                             "module_prefixes": []}
    if not runtime_env:
        return saved
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved["env"][k] = os.environ.get(k)
            os.environ[k] = str(v)
        pip = runtime_env.get("pip")
        if pip:
            site = ensure_pip_env([pip] if isinstance(pip, str) else pip,
                                  worker)
            sys.path.insert(0, str(site))
            saved["sys_path"].append(str(site))
            # restore() purges modules imported from here so the pooled
            # worker's import state is not polluted for the next task
            saved["module_prefixes"].append(str(site))
        conda = runtime_env.get("conda")
        if conda:
            env_dir = ensure_conda_env(conda, worker)
            # in-process application mirrors the pip plugin: the env's
            # site-packages prefixes sys.path (python-version-compatible
            # packages), its bin prefixes PATH for subprocess tools;
            # module purge keeps the pooled worker clean
            for sp in sorted(env_dir.glob("lib/python*/site-packages")):
                sys.path.insert(0, str(sp))
                saved["sys_path"].append(str(sp))
                saved["module_prefixes"].append(str(sp))
            saved["env"].setdefault("PATH", os.environ.get("PATH"))
            os.environ["PATH"] = f"{env_dir / 'bin'}:" + \
                os.environ.get("PATH", "")
        wd = runtime_env.get("working_dir")
        if wd:
            local = ensure_local(wd, worker)
            saved["cwd"] = os.getcwd()
            os.chdir(local)
            sys.path.insert(0, str(local))
            saved["sys_path"].append(str(local))
        for m in (runtime_env.get("py_modules") or []):
            local = ensure_local(m, worker)
            sys.path.insert(0, str(local))
            saved["sys_path"].append(str(local))
    except BaseException:
        restore(saved)
        raise
    return saved


def restore(saved: Dict[str, Any]) -> None:
    for k, v in saved.get("env", {}).items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if saved.get("cwd"):
        try:
            os.chdir(saved["cwd"])
        except OSError:
            pass
    for p in saved.get("sys_path", []):
        try:
            sys.path.remove(p)
        except ValueError:
            pass
    prefixes = tuple(saved.get("module_prefixes") or ())
    if prefixes:
        for name, mod in list(sys.modules.items()):
            f = getattr(mod, "__file__", None)
            if f and f.startswith(prefixes):
                del sys.modules[name]
