"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Reference: ``python/ray/_private/runtime_env/`` (SURVEY.md §2.3) — the
driver uploads ``working_dir``/``py_modules`` into the GCS KV
(content-addressed zips); workers download+extract into a session cache,
chdir into the working dir and extend ``sys.path``, then undo after the
task (env application is per-task here since workers are pooled).

Omitted relative to the reference: pip/conda/container isolation — those
need network/process isolation this environment doesn't have; env shape is
validated so unsupported keys fail loudly rather than silently no-op.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules", "config"}
_URI_PREFIX = "kv://runtime_env/"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_ZIP_BYTES = 64 * 1024 * 1024


def validate(runtime_env: Optional[dict]) -> None:
    if not runtime_env:
        return
    unknown = set(runtime_env) - SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; supported: "
            f"{sorted(SUPPORTED_KEYS)} (pip/conda/container isolation is "
            f"not available in this build)")


# ---------------------------------------------------------------- packaging
def _zip_dir(path: Path) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for p in sorted(path.rglob("*")):
            if any(part in _EXCLUDE_DIRS for part in p.parts):
                continue
            if p.is_file():
                # fixed date_time → content-addressed hash is stable
                zi = zipfile.ZipInfo(str(p.relative_to(path)),
                                     date_time=(1980, 1, 1, 0, 0, 0))
                zi.external_attr = (p.stat().st_mode & 0xFFFF) << 16
                zf.writestr(zi, p.read_bytes())
    data = buf.getvalue()
    if len(data) > _MAX_ZIP_BYTES:
        raise ValueError(f"working_dir zip is {len(data)} bytes "
                         f"(limit {_MAX_ZIP_BYTES}); exclude large data")
    return data


def upload_dir(path: str, worker) -> str:
    """Zip + content-address + store in GCS KV; returns kv:// URI."""
    p = Path(path).resolve()
    if not p.is_dir():
        raise ValueError(f"runtime_env directory not found: {path}")
    data = _zip_dir(p)
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"runtime_env/{digest}"
    uri = _URI_PREFIX + digest
    if not worker.rpc("kv_get", key=key).get("value"):
        worker.rpc("kv_put", key=key, value=data)
    return uri


def prepare(runtime_env: Optional[dict], worker) -> Optional[dict]:
    """Driver-side: resolve local paths into uploaded URIs (at submit)."""
    if not runtime_env:
        return runtime_env
    validate(runtime_env)
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith(_URI_PREFIX):
        env["working_dir"] = upload_dir(wd, worker)
    mods = env.get("py_modules")
    if mods:
        env["py_modules"] = [
            m if str(m).startswith(_URI_PREFIX) else upload_dir(m, worker)
            for m in mods]
    return env


# --------------------------------------------------------------- worker side
def ensure_local(uri: str, worker) -> Path:
    """Fetch + extract a kv:// URI into the session cache; idempotent."""
    digest = uri[len(_URI_PREFIX):]
    if worker.session is not None:
        root = Path(worker.session.path)
    else:  # remote worker: no session dir on this host.  Per-user dir:
        # a world-shared path would let another user pre-seed
        # content-addressed entries (and breaks on mkdir permissions).
        import getpass
        import stat as stat_mod
        import tempfile
        root = Path(tempfile.gettempdir()) / f"rtpu_remote_{getpass.getuser()}"
        root.mkdir(mode=0o700, exist_ok=True)
        st = root.stat()  # reject a pre-seeded foreign dir (mkdir with
        # exist_ok succeeds silently on an attacker-owned path)
        if st.st_uid != os.getuid() or stat_mod.S_IMODE(st.st_mode) != 0o700:
            raise PermissionError(
                f"{root} exists with wrong owner/mode; refusing to use it "
                f"as the runtime_env cache")
    cache = root / "runtime_env" / digest
    if cache.exists():
        return cache
    raw = worker.rpc("kv_get", key=f"runtime_env/{digest}").get("value")
    if raw is None:
        raise FileNotFoundError(f"runtime_env blob missing from KV: {uri}")
    tmp = cache.with_name(cache.name + f".tmp{os.getpid()}")
    tmp.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(raw)) as zf:
        zf.extractall(tmp)
    try:
        tmp.rename(cache)  # atomic publish; losers clean up
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return cache


def apply(runtime_env: Optional[dict], worker) -> Dict[str, Any]:
    """Apply working_dir/py_modules/env_vars; returns restore state.

    Exception-safe: a failure mid-application (missing KV blob, corrupt
    zip) restores whatever was already applied before re-raising, so the
    pooled worker process is left clean for the next task."""
    saved: Dict[str, Any] = {"env": {}, "cwd": None, "sys_path": []}
    if not runtime_env:
        return saved
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved["env"][k] = os.environ.get(k)
            os.environ[k] = str(v)
        wd = runtime_env.get("working_dir")
        if wd:
            local = ensure_local(wd, worker)
            saved["cwd"] = os.getcwd()
            os.chdir(local)
            sys.path.insert(0, str(local))
            saved["sys_path"].append(str(local))
        for m in (runtime_env.get("py_modules") or []):
            local = ensure_local(m, worker)
            sys.path.insert(0, str(local))
            saved["sys_path"].append(str(local))
    except BaseException:
        restore(saved)
        raise
    return saved


def restore(saved: Dict[str, Any]) -> None:
    for k, v in saved.get("env", {}).items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if saved.get("cwd"):
        try:
            os.chdir(saved["cwd"])
        except OSError:
            pass
    for p in saved.get("sys_path", []):
        try:
            sys.path.remove(p)
        except ValueError:
            pass
