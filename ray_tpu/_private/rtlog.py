"""Logging for ray_tpu processes.

Analog of the reference's spdlog-based ``RAY_LOG`` plus the Python log monitor
that prefixes driver-shipped worker lines with ``(pid=...)`` (reference:
``src/ray/util/logging.h``, ``python/ray/_private/log_monitor.py``;
SURVEY.md §5.5).  Workers log to ``<session>/logs/<component>.log``; lines a
worker prints are also forwarded to the driver over the control-plane socket
and re-emitted with a ``(component pid=N)`` prefix.
"""

from __future__ import annotations

import logging
import os
import sys
from pathlib import Path
from typing import Optional

from ray_tpu._private.config import GLOBAL_CONFIG

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
# Idempotency is tracked PER HANDLER, not per process: the old module
# global `_configured` made setup() first-caller-wins — a second call
# with a log_dir (e.g. a client-mode init followed by attaching to a
# session) never got its file handler, and a different component name
# was silently ignored.
_stream_configured = False
# component -> (resolved log_dir, FileHandler): ONE file handler per
# component, replaced when a later session points it at a new dir — an
# init→shutdown→init cycle must not leave session A's file receiving
# session B's records (and leaking an fd) forever
_file_handlers: dict = {}


def setup(component: str, log_dir: Optional[Path] = None) -> logging.Logger:
    """Configure the process-wide ray_tpu logger; returns the root logger.

    Idempotent per handler: the stderr handler attaches once per
    process, and each distinct (component, log_dir) pair attaches its
    file handler exactly once — repeated calls never duplicate handlers
    and never drop a newly requested log file."""
    global _stream_configured
    logger = logging.getLogger("ray_tpu")
    fmt = logging.Formatter(_FORMAT)
    if not _stream_configured:
        # level only on first configuration: later setup() calls (serve
        # controller boot, session attach) must not clobber a level the
        # user set programmatically mid-session
        logger.setLevel(GLOBAL_CONFIG.log_level)
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        logger.propagate = False
        _stream_configured = True
    if log_dir is not None:
        dirkey = str(Path(log_dir).resolve())
        prev = _file_handlers.get(component)
        if prev is None or prev[0] != dirkey:
            if prev is not None:  # new session dir: retire the old file
                logger.removeHandler(prev[1])
                prev[1].close()
            fh = logging.FileHandler(
                str(Path(log_dir) / f"{component}-{os.getpid()}.log"))
            fh.setFormatter(fmt)
            logger.addHandler(fh)
            _file_handlers[component] = (dirkey, fh)
    return logger


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"ray_tpu.{name}")
