"""Logging for ray_tpu processes.

Analog of the reference's spdlog-based ``RAY_LOG`` plus the Python log monitor
that prefixes driver-shipped worker lines with ``(pid=...)`` (reference:
``src/ray/util/logging.h``, ``python/ray/_private/log_monitor.py``;
SURVEY.md §5.5).  Workers log to ``<session>/logs/<component>.log``; lines a
worker prints are also forwarded to the driver over the control-plane socket
and re-emitted with a ``(component pid=N)`` prefix.
"""

from __future__ import annotations

import logging
import os
import sys
from pathlib import Path
from typing import Optional

from ray_tpu._private.config import GLOBAL_CONFIG

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
_configured = False


def setup(component: str, log_dir: Optional[Path] = None) -> logging.Logger:
    """Configure the process-wide ray_tpu logger once; returns the root logger."""
    global _configured
    logger = logging.getLogger("ray_tpu")
    if not _configured:
        logger.setLevel(GLOBAL_CONFIG.log_level)
        fmt = logging.Formatter(_FORMAT)
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        if log_dir is not None:
            fh = logging.FileHandler(str(Path(log_dir) / f"{component}-{os.getpid()}.log"))
            fh.setFormatter(fmt)
            logger.addHandler(fh)
        logger.propagate = False
        _configured = True
    return logger


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"ray_tpu.{name}")
