"""Replicated GCS ledger: WAL + streaming replication + warm standby.

Reference analog: GCS fault tolerance via external Redis persistence +
reconnecting clients (SURVEY.md §5.3).  PR 8 made the GCS a *ledger*
raylets reconcile against across restarts; until this module its
durability was a debounced pickle snapshot (`gcs._persist_loop`, ~0.5s
crash window, no fsync) and recovery meant manually booting a new head
over the same session dir.  This module closes both gaps (DESIGN.md
§4l):

- **Write-ahead log.**  Every durable ledger mutation (KV puts/deletes,
  function exports, actor/named-actor/PG transitions, shm object-meta
  seals/deletes, driver registrations) is captured at the GCS handler
  layer as one idempotent table *op* and appended — crc-framed, fsynced
  in drain batches — to ``<session>/gcs_state/wal-<epoch>-<seq>.log``.
  A head restart replays the WAL tail on top of the newest good
  snapshot, so the crash window shrinks from the snapshot debounce to
  one drain batch.  Replay is idempotent by construction (every op is a
  keyed upsert/delete), a torn tail record is ignored, and a corrupt
  mid-file record quarantines the segment.
- **Warm standby.**  A :class:`StandbyHead` dials the primary's GCS
  socket, negotiates ``wire.PROTO_REPL`` and converts the connection
  into a one-way replication stream (``repl_attach``): first a full
  durable-state snapshot (``repl_snapshot``), then incremental
  ``repl_wal`` record batches the standby applies into live tables,
  periodic ``repl_heartbeat`` liveness, and ``repl_tsdb`` metric-ring
  deltas so the head's 48h memory survives it.  On primary death
  (stream EOF with the endpoint dead, or missed heartbeats) the standby
  *promotes*: it writes its tables as a snapshot, replays any WAL tail
  the dead primary fsynced but never streamed, and boots a real
  :class:`~ray_tpu._private.gcs.GcsServer` over the session dir — the
  listener re-binds the same ``gcs.sock`` path, so raylets re-attach
  via the PR-8 path and clients/workers re-dial through their bounded-
  backoff reconnects with zero task loss.
- **Split-brain guard.**  Every head start claims the next *ledger
  epoch* in ``<session>/gcs_state/epoch`` (fsynced).  The primary's
  replication drain thread polls the file at the heartbeat cadence; the
  moment it observes a HIGHER epoch than its own it fences the server —
  a fenced GCS refuses every mutating RPC, so a promoted standby can
  never race a still-alive old primary for the ledger.

Locking (``REPL_LOCK_DAG`` in lock_watchdog.py; rtlint-enforced): the
hub's one no-block leaf ``_lock`` guards only the seq counter, the
record buffer, and the adoption queue — GCS handler threads append
under it in O(1) while holding GCS locks; all file I/O and every
standby send happen on the single ``gcs-repl`` drain thread with no
lock held.
"""

from __future__ import annotations

import binascii
import os
import pickle
import struct
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import lock_watchdog, protocol, rtlog, wire

logger = rtlog.get("replication")


class ReplUnsupported(ConnectionError):
    """The primary does not speak wire.PROTO_REPL."""

# WAL segment header: magic + u64 ledger epoch + u64 first record seq.
_WAL_MAGIC = b"RTWAL1\n\0"
_WAL_HDR = struct.Struct(">QQ")
# record framing: u32 payload length + u32 crc32(payload); payload is
# pickle.dumps((seq, op))
_REC_HDR = struct.Struct(">II")
_REC_MAX = 64 * 1024 * 1024  # a saner-than-u32 bound on one record


# --------------------------------------------------------------- ledger ops
# One op = one idempotent upsert/delete on the durable tables — the same
# set ``gcs._capture_durable_state`` snapshots.  Applying an op twice is
# identical to applying it once, which is what makes snapshot+WAL replay
# and at-least-once streaming safe without coordination:
#   ("kv", ns, key, value|None)         value None deletes
#   ("fn", fn_id, blob)
#   ("actor", actor_id, rec|None)       rec as snapshotted; None = gone
#   ("named", namespace, name, aid|None)
#   ("pg", pg_id, rec|None)
#   ("shm", oid, size|None)
#   ("driver", worker_id)


def new_ledger_state() -> Dict[str, Any]:
    """Empty durable-table state, shaped exactly like the snapshot dict
    ``gcs._capture_durable_state`` produces (minus the wal bookkeeping
    keys) so the two compare directly in the equivalence oracle."""
    return {"kv": {}, "functions": {}, "named_actors": {}, "actors": {},
            "pgs": {}, "shm_objects": {}, "driver_ids": set()}


def apply_op(state: Dict[str, Any], op: Tuple) -> None:
    """Apply one ledger op to a state dict (idempotent upsert/delete)."""
    kind = op[0]
    if kind == "kv":
        _, ns, key, value = op
        table = state["kv"].setdefault(ns, {})
        if value is None:
            table.pop(key, None)
            if not table:
                state["kv"].pop(ns, None)
        else:
            table[key] = value
    elif kind == "fn":
        state["functions"][op[1]] = op[2]
    elif kind == "actor":
        _, aid, rec = op
        if rec is None:
            state["actors"].pop(aid, None)
        else:
            state["actors"][aid] = rec
    elif kind == "named":
        _, ns, name, aid = op
        if aid is None:
            state["named_actors"].pop((ns, name), None)
        else:
            state["named_actors"][(ns, name)] = aid
    elif kind == "pg":
        _, pid, rec = op
        if rec is None:
            state["pgs"].pop(pid, None)
        else:
            state["pgs"][pid] = rec
    elif kind == "shm":
        _, oid, size = op
        if size is None:
            state["shm_objects"].pop(oid, None)
        else:
            state["shm_objects"][oid] = size
    elif kind == "driver":
        state["driver_ids"].add(op[1])
    else:
        raise ValueError(f"unknown ledger op kind {kind!r}")


# ------------------------------------------------------------ epoch fence
def gcs_state_dir(session_path) -> Path:
    return Path(session_path) / "gcs_state"


def _epoch_path(session_path) -> Path:
    return gcs_state_dir(session_path) / "epoch"


def read_epoch(session_path) -> int:
    try:
        return int(_epoch_path(session_path).read_text().strip() or 0)
    except (FileNotFoundError, ValueError):
        return 0


def claim_epoch(session_path) -> int:
    """Claim the next ledger epoch (fsynced tmp + rename so a crash can
    never leave a torn epoch file).  Called once per head start; any
    still-alive older head observes the bump and fences itself.

    The read-increment-write runs under an exclusive flock on a
    sidecar lock file: a standby auto-promoting at the same moment an
    operator manually boots a replacement head must NOT both claim the
    same epoch — equal epochs would fence neither (the guard fires
    only on a strictly higher value) and the two heads would interleave
    ledgers in one namespace."""
    import fcntl
    path = _epoch_path(session_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    lock_fd = os.open(str(path.with_suffix(".lock")),
                      os.O_CREAT | os.O_RDWR, 0o600)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        epoch = read_epoch(session_path) + 1
        tmp = path.with_suffix(".tmp")
        fd = os.open(str(tmp), os.O_CREAT | os.O_TRUNC | os.O_WRONLY,
                     0o600)
        try:
            os.write(fd, str(epoch).encode())
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        _fsync_dir(path.parent)
        return epoch
    finally:
        os.close(lock_fd)  # releases the flock


def _fsync_dir(path: Path) -> None:
    """fsync a directory so a rename into it survives a host crash."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ------------------------------------------------------- snapshot on disk
def write_snapshot_file(snapshot_path: Path, state: Dict[str, Any]) -> None:
    """Write the durable-state snapshot crash-safely: fsync the tmp file
    BEFORE the rename and the directory after it (os.replace alone can
    leave a zero-length "newest" snapshot after a host crash), and keep
    the previous generation as ``<name>.prev`` so a torn newest file
    degrades to stale-but-consistent instead of fresh-start."""
    snapshot_path.parent.mkdir(parents=True, exist_ok=True)
    tmp = snapshot_path.with_suffix(".tmp")
    fd = os.open(str(tmp), os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o600)
    try:
        os.write(fd, pickle.dumps(state))
        os.fsync(fd)
    finally:
        os.close(fd)
    prev = snapshot_path.with_name(snapshot_path.name + ".prev")
    try:
        os.replace(snapshot_path, prev)  # demote the old generation
    except FileNotFoundError:
        pass
    os.replace(tmp, snapshot_path)
    _fsync_dir(snapshot_path.parent)


def _load_snapshot(path: Path) -> Optional[Dict[str, Any]]:
    try:
        raw = path.read_bytes()
        if not raw:
            raise ValueError("zero-length snapshot")
        state = pickle.loads(raw)
        if not isinstance(state, dict) or "kv" not in state:
            raise ValueError("snapshot missing durable tables")
        return state
    except FileNotFoundError:
        return None
    except Exception:  # noqa: BLE001 - torn/corrupt generation
        logger.exception("unreadable snapshot %s", path)
        return None


def load_durable_state(session_path,
                       snapshot_path: Optional[Path] = None
                       ) -> Optional[Dict[str, Any]]:
    """Newest consistent durable state: the newest readable snapshot
    generation (torn newest falls back to ``.prev`` instead of fresh
    start) plus the fsynced WAL tail of the snapshot's ledger epoch
    replayed on top (records with seq > the snapshot's ``wal_seq``).
    Returns None when no generation is readable (fresh start)."""
    if snapshot_path is None:
        snapshot_path = gcs_state_dir(session_path) / "snapshot.pkl"
    state = _load_snapshot(snapshot_path)
    if state is None:
        prev = snapshot_path.with_name(snapshot_path.name + ".prev")
        state = _load_snapshot(prev)
        if state is None:
            # no snapshot generation at all: a head that died before
            # its FIRST snapshot write.  Its WAL is genesis-complete
            # (rotation only ever deletes segments a successful
            # snapshot covered), so replay reconstructs everything.
            return _replay_genesis(session_path)
        logger.warning("newest snapshot unreadable; restored the "
                       "previous generation %s", prev)
    epoch = int(state.get("ledger_epoch") or 0)
    base_seq = int(state.get("wal_seq") or 0)
    applied = 0
    corrupt = False
    # The snapshot's own epoch tail first, then every HIGHER epoch
    # ascending: a successor head that restored this same state,
    # claimed epoch+k, served fsynced mutations, and died before its
    # FIRST snapshot write left its whole ledger delta only in its own
    # epoch's WAL.  Chaining is sound because each such successor's
    # boot state was exactly the replay reconstructed so far; a
    # higher-epoch log not starting at seq 1 contradicts that (its own
    # snapshot existed once and is lost) and stops the chain there.
    epochs = sorted({_segment_epoch(p) for p in wal_segments(session_path)
                     if _segment_epoch(p) >= epoch} | {epoch})
    for ep in epochs:
        if corrupt:
            break  # records past a corrupt region may depend on the gap
        segs = wal_segments(session_path, ep)
        if ep > epoch and segs:
            raw0 = segs[0].read_bytes()
            first_start = _WAL_HDR.unpack_from(raw0, len(_WAL_MAGIC))[1] \
                if len(raw0) >= len(_WAL_MAGIC) + _WAL_HDR.size else 1
            if first_start != 1:
                logger.error("epoch %d WAL starts at seq %d with no "
                             "epoch-%d snapshot: stopping the replay "
                             "chain here", ep, first_start, ep)
                break
        for seg in segs:
            records, clean = read_wal_records(seg)
            for seq, op in records:
                if ep == epoch and seq <= base_seq:
                    continue  # covered by the snapshot
                try:
                    apply_op(state, op)
                    applied += 1
                except Exception:  # noqa: BLE001 - one undecodable op
                    # must not discard the rest of the consistent prefix
                    logger.exception("WAL op replay failed (seq %d)",
                                     seq)
            if not clean:
                quarantine_wal(seg)
                corrupt = True
                break
    if applied:
        logger.info("replayed %d WAL record(s) on top of the snapshot",
                    applied)
    return state


def _replay_genesis(session_path) -> Optional[Dict[str, Any]]:
    """Durable state with NO readable snapshot generation: replay every
    epoch's WAL from empty, ascending.  Sound because (a) each head that
    found no snapshot restored exactly this replay of the epochs before
    it, so consecutive epochs' logs compose, and (b) rotation only
    deletes segments after a snapshot write SUCCEEDED — no snapshot on
    disk means no segment was ever dropped.  A first segment that does
    not start at seq 1 contradicts (b) (a snapshot existed and was
    lost): bail to fresh-start rather than restore a state with a
    silent hole."""
    by_epoch: Dict[int, List[Path]] = {}
    for seg in wal_segments(session_path):
        by_epoch.setdefault(_segment_epoch(seg), []).append(seg)
    if not by_epoch:
        return None
    state = new_ledger_state()
    last_epoch = 0
    last_seq = 0
    corrupt = False
    for epoch in sorted(by_epoch):
        if corrupt:
            break  # later epochs build on the gapped prefix: stop
        segs = by_epoch[epoch]
        raw0 = segs[0].read_bytes()
        first_start = _WAL_HDR.unpack_from(raw0, len(_WAL_MAGIC))[1] \
            if len(raw0) >= len(_WAL_MAGIC) + _WAL_HDR.size else 1
        if first_start != 1:
            logger.error("no snapshot but epoch %d WAL starts at seq "
                         "%d: a covered prefix was lost — refusing a "
                         "holey restore", epoch, first_start)
            return None
        for seg in segs:
            records, clean = read_wal_records(seg)
            for seq, op in records:
                try:
                    apply_op(state, op)
                except Exception:  # noqa: BLE001 - keep the prefix
                    logger.exception("WAL op replay failed (seq %d)",
                                     seq)
                last_seq = int(seq)
            last_epoch = epoch
            if not clean:
                quarantine_wal(seg)
                corrupt = True
                break
    state["ledger_epoch"] = last_epoch
    state["wal_seq"] = last_seq
    logger.info("restored durable state from genesis WAL replay "
                "(epoch %d, seq %d)", last_epoch, last_seq)
    return state


# ----------------------------------------------------------------- WAL files
def _segment_epoch(path: Path) -> int:
    """Ledger epoch encoded in a WAL segment's file name."""
    try:
        return int(path.name[4:-4].split("-")[0])
    except (ValueError, IndexError):
        return 0


def wal_segment_path(session_path, epoch: int, start_seq: int) -> Path:
    return gcs_state_dir(session_path) / f"wal-{epoch:08d}-{start_seq:012d}.log"


def wal_segments(session_path, epoch: Optional[int] = None) -> List[Path]:
    """WAL segment files (of one ledger epoch, or all), start-seq order."""
    out = []
    try:
        names = os.listdir(str(gcs_state_dir(session_path)))
    except FileNotFoundError:
        return []
    for name in names:
        if not (name.startswith("wal-") and name.endswith(".log")):
            continue
        parts = name[4:-4].split("-")
        if len(parts) != 2:
            continue
        try:
            e, s = int(parts[0]), int(parts[1])
        except ValueError:
            continue
        if epoch is None or e == epoch:
            out.append((e, s, gcs_state_dir(session_path) / name))
    out.sort()
    return [p for _, _, p in out]


def encode_wal_record(seq: int, op: Tuple) -> bytes:
    payload = pickle.dumps((seq, op), protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > _REC_MAX:
        # the READER treats length > _REC_MAX as corruption; an
        # unwritable-size record must fail HERE (the drain batch skips
        # it with a log, like an unpicklable op) — appending it would
        # quarantine the whole segment at the next replay
        raise ValueError(f"WAL record of {len(payload)} bytes exceeds "
                         f"the {_REC_MAX} byte bound")
    return _REC_HDR.pack(len(payload),
                         binascii.crc32(payload) & 0xFFFFFFFF) + payload


def read_wal_records(path: Path) -> Tuple[List[Tuple[int, Tuple]], bool]:
    """Decode one WAL segment → (records, clean).

    ``clean`` is False only for genuine CORRUPTION: a complete record
    whose crc fails, a bad header, or an impossible length.  A record
    truncated at EOF is a *torn tail* — the expected artifact of a crash
    mid-append — and stops the read silently (clean stays True).
    Decoding stops at the first bad record either way; the consistent
    prefix is all a replayer may trust."""
    records: List[Tuple[int, Tuple]] = []
    try:
        raw = path.read_bytes()
    except OSError:
        return records, False
    hdr_len = len(_WAL_MAGIC) + _WAL_HDR.size
    if len(raw) < hdr_len:
        # a header torn mid-write: an empty segment, not corruption
        return records, len(raw) == 0 or _WAL_MAGIC.startswith(raw[:8])
    if raw[:len(_WAL_MAGIC)] != _WAL_MAGIC:
        return records, False
    off = hdr_len
    n = len(raw)
    while off < n:
        if off + _REC_HDR.size > n:
            return records, True  # torn tail: header cut at EOF
        length, crc = _REC_HDR.unpack_from(raw, off)
        if length > _REC_MAX:
            return records, False
        if off + _REC_HDR.size + length > n:
            return records, True  # torn tail: payload cut at EOF
        payload = raw[off + _REC_HDR.size:off + _REC_HDR.size + length]
        if binascii.crc32(payload) & 0xFFFFFFFF != crc:
            return records, False  # complete record, bad crc: corrupt
        try:
            seq, op = pickle.loads(payload)
            records.append((int(seq), tuple(op)))
        except Exception:  # noqa: BLE001 - crc passed but undecodable
            return records, False
        off += _REC_HDR.size + length
    return records, True


def quarantine_wal(path: Path) -> Optional[Path]:
    """Move a corrupt WAL segment aside (kept for forensics, never
    replayed again)."""
    target = path.with_name(path.name + f".corrupt-{int(time.time())}")
    try:
        os.replace(path, target)
        logger.error("quarantined corrupt WAL segment %s -> %s",
                     path.name, target.name)
        return target
    except OSError:
        return None


# ------------------------------------------------------------------- the hub
class ReplicationHub:
    """Primary-side replication: WAL appends + standby streaming.

    Handler threads call :meth:`record` (O(1) buffer append under the
    no-block leaf ``_lock``, legal under any GCS lock); the single
    ``gcs-repl`` drain thread owns every file write, fsync, and standby
    send, plus heartbeats, TSDB-delta shipping, WAL rotation, and the
    split-brain epoch-fence poll."""

    def __init__(self, session_path, epoch: int,
                 snapshot_cb: Callable[[], Dict[str, Any]],
                 tsdb_export_cb: Optional[Callable[[], Any]] = None,
                 on_fenced: Optional[Callable[[int], None]] = None,
                 fsync: bool = True):
        from ray_tpu._private.config import GLOBAL_CONFIG
        self.session_path = Path(session_path)
        self.epoch = int(epoch)
        self._snapshot_cb = snapshot_cb
        self._tsdb_export_cb = tsdb_export_cb
        self._on_fenced = on_fenced
        self._fsync = fsync
        self._hb_period = max(0.05, GLOBAL_CONFIG.gcs_repl_heartbeat_s)
        self._tsdb_period = max(self._hb_period,
                                GLOBAL_CONFIG.gcs_repl_tsdb_interval_s)
        self._lock = threading.Lock()  # no-block leaf (REPL_LOCK_DAG)
        self._seq = 0                        # guarded by: _lock
        self._buf: List[Tuple[int, Tuple]] = []  # guarded by: _lock
        self._pending_conns: List = []       # guarded by: _lock
        self._rotate_to: Optional[int] = None  # guarded by: _lock
        self._records_total = 0              # guarded by: _lock
        # drain-thread-owned state (single owner, never locked):
        self._standbys: List = []
        self._segments: List[Tuple[int, int, Path]] = []  # (start, last, p)
        self._wal_fd: Optional[int] = None
        self._wal_start = 1
        self._wal_last = 0
        self._tsdb_cursor = 0.0
        self._last_tsdb = 0.0
        self.fenced = False
        self._stop = threading.Event()
        self._event = threading.Event()
        self._open_segment(1)
        self._thread = threading.Thread(target=self._drain_loop,
                                        name="gcs-repl", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- handler side
    def record(self, *op) -> int:
        """Append one ledger op (called by GCS handler threads, any GCS
        lock held — O(1), never blocks)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._buf.append((seq, tuple(op)))
            self._records_total += 1
        self._event.set()
        return seq

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def adopt_standby(self, conn) -> None:  # rtlint: owns(conn)
        """Hand an attached (``repl_attach``) connection to the drain
        thread, which bootstraps it with a snapshot and streams from
        there.  The hub owns the conn from here on."""
        with self._lock:
            self._pending_conns.append(conn)
        self._event.set()

    def rotate(self, covered_seq: int) -> None:
        """A durable snapshot covering records <= ``covered_seq`` was
        written: the drain thread rolls to a fresh segment and unlinks
        fully-covered ones."""
        with self._lock:
            self._rotate_to = max(covered_seq, self._rotate_to or 0)
        self._event.set()

    def info(self) -> Dict[str, Any]:
        with self._lock:
            return {"epoch": self.epoch, "seq": self._seq,
                    "records_total": self._records_total,
                    "standbys": len(self._standbys),
                    "fenced": self.fenced}

    def close(self) -> None:
        self._stop.set()
        self._event.set()
        self._thread.join(timeout=5.0)
        # the drain thread exited (or is wedged past the join timeout —
        # daemon, so it cannot outlive the process): discharge the fd
        # and every standby conn
        if self._wal_fd is not None:
            try:
                os.close(self._wal_fd)
            except OSError:
                pass
            self._wal_fd = None
        for conn in self._standbys:
            try:
                conn.close()
            except OSError:
                pass
        self._standbys = []
        with self._lock:
            pending, self._pending_conns = self._pending_conns, []
        for conn in pending:
            try:
                conn.close()
            except OSError:
                pass

    # --------------------------------------------------------- drain thread
    def _open_segment(self, start_seq: int) -> None:
        path = wal_segment_path(self.session_path, self.epoch, start_seq)
        path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(str(path), os.O_CREAT | os.O_TRUNC | os.O_WRONLY,
                     0o600)
        # fd owned by the hub from here (close() discharges it) BEFORE
        # the header write, so a full-disk failure cannot strand it
        self._wal_fd = fd
        self._wal_path = path
        try:
            os.write(fd, _WAL_MAGIC + _WAL_HDR.pack(self.epoch, start_seq))
            if self._fsync:
                os.fsync(fd)
        except OSError:
            logger.exception("WAL segment header write failed")
        self._wal_start = start_seq
        self._wal_last = start_seq - 1

    def _drain_loop(self) -> None:
        last_hb = 0.0
        while not self._stop.is_set():
            with lock_watchdog.bounded_block("repl.hub_tick",
                                             bound=self._hb_period):
                self._event.wait(timeout=self._hb_period)
            self._event.clear()
            if self._stop.is_set():
                return
            try:
                with self._lock:
                    batch, self._buf = self._buf, []
                    pending, self._pending_conns = self._pending_conns, []
                    rotate_to, self._rotate_to = self._rotate_to, None
                if batch and not self.fenced:
                    # stream FIRST: standby freshness must not pay the
                    # WAL fsync's disk latency (a standby is itself a
                    # durability replica — it may legitimately hold
                    # records the local fsync hasn't confirmed yet)
                    self._send_all({"kind": "repl_wal", "rid": None,
                                    "epoch": self.epoch,
                                    "records": list(batch)})
                    self._write_batch(batch)
                # (a FENCED hub discards the batch: the promoted head's
                # snapshot is stamped with THIS epoch, so any record
                # this head appends post-fence would replay on top of
                # the new ledger at the next restore and diverge it)
                if rotate_to is not None:
                    self._do_rotate(rotate_to)
                for conn in pending:
                    if self.fenced:
                        # a stale snapshot must not bootstrap anyone
                        try:
                            conn.close()
                        except OSError:
                            pass
                        continue
                    self._bootstrap_standby(conn)
                now = time.monotonic()
                if now - last_hb >= self._hb_period:
                    last_hb = now
                    self._heartbeat_tick()
                if self._tsdb_export_cb is not None and \
                        now - self._last_tsdb >= self._tsdb_period:
                    self._last_tsdb = now
                    self._tsdb_tick()
            except Exception:  # noqa: BLE001 - the only drain thread:
                # an unexpected error must not end replication forever
                logger.exception("replication drain pass failed")

    def _write_batch(self, batch: List[Tuple[int, Tuple]]) -> None:
        if self._wal_fd is None:
            return
        chunks = []
        for seq, op in batch:
            try:
                chunks.append(encode_wal_record(seq, op))
            except Exception:  # noqa: BLE001 - an unpicklable op (user
                # payloads live inside kv values / actor specs) must not
                # poison the whole batch
                logger.exception("WAL encode failed (seq %d)", seq)
        if not chunks:
            return
        try:
            protocol.write_all(self._wal_fd, b"".join(chunks))
            if self._fsync:
                os.fsync(self._wal_fd)  # group commit: one fsync/batch
        except OSError:
            logger.exception("WAL append failed")
            return
        self._wal_last = batch[-1][0]
        self._count_metric(len(batch))

    @staticmethod
    def _count_metric(n: int) -> None:
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG
            if not GLOBAL_CONFIG.metrics_enabled:
                return
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_gcs_wal_records_total").inc(n)
        except Exception:  # noqa: BLE001 - telemetry best-effort
            pass

    def _do_rotate(self, covered_seq: int) -> None:
        if self._wal_fd is None:
            return
        self._segments.append((self._wal_start, self._wal_last,
                               self._wal_path))
        try:
            os.close(self._wal_fd)
        except OSError:
            pass
        self._wal_fd = None
        self._open_segment(self._wal_last + 1)
        keep = []
        for start, last, path in self._segments:
            if last <= covered_seq:
                try:
                    os.unlink(str(path))
                except OSError:
                    pass
            else:
                keep.append((start, last, path))
        self._segments = keep

    def _bootstrap_standby(self, conn) -> None:
        """Snapshot + activate one adopted standby conn (drain thread).
        The capture callback takes GCS locks; this thread holds none.
        Records drained AFTER this point stream to the standby; any
        overlap with the captured state re-applies idempotently."""
        try:
            state = self._snapshot_cb()
            wire.conn_send(conn, {"kind": "repl_snapshot", "rid": None,
                                  "epoch": self.epoch,
                                  "wal_seq": int(state.get("wal_seq") or 0),
                                  "state": state}, wire.PROTO_REPL)
        except Exception:  # noqa: BLE001 - standby died mid-bootstrap
            logger.exception("standby bootstrap failed")
            try:
                conn.close()
            except OSError:
                pass
            return
        self._standbys.append(conn)
        self._set_standby_gauge()
        logger.info("standby attached (%d active)", len(self._standbys))

    def _send_all(self, msg: dict) -> None:
        dead = []
        for conn in self._standbys:
            try:
                wire.conn_send(conn, msg, wire.PROTO_REPL)
            except (OSError, ValueError, EOFError):
                dead.append(conn)
        for conn in dead:
            self._standbys.remove(conn)
            try:
                conn.close()
            except OSError:
                pass
        if dead:
            self._set_standby_gauge()
            logger.warning("standby disconnected (%d active)",
                           len(self._standbys))

    def standby_count(self) -> int:
        """Attached standbys right now.  ``_standbys`` is drain-thread-
        owned; this cross-thread ``len`` read (the autopilot's standby
        reflex, §4n) is a benign snapshot — at worst one attach/detach
        stale, which the next tick corrects."""
        return len(self._standbys)

    def _set_standby_gauge(self) -> None:
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG
            if not GLOBAL_CONFIG.metrics_enabled:
                return
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_gcs_repl_standbys").set(len(self._standbys))
        except Exception:  # noqa: BLE001 - telemetry best-effort
            pass

    def _heartbeat_tick(self) -> None:
        if self._standbys:
            with self._lock:
                seq = self._seq
            self._send_all({"kind": "repl_heartbeat", "rid": None,
                            "epoch": self.epoch, "seq": seq})
        # split-brain fence: a HIGHER claimed epoch in the session dir
        # means a standby promoted over us — stop mutating the ledger
        if not self.fenced:
            seen = read_epoch(self.session_path)
            if seen > self.epoch:
                self.fenced = True
                logger.error("ledger epoch %d observed (own %d): this "
                             "head is fenced and refuses writes",
                             seen, self.epoch)
                if self._on_fenced is not None:
                    try:
                        self._on_fenced(seen)
                    except Exception:  # noqa: BLE001
                        logger.exception("fence callback failed")

    def _tsdb_tick(self) -> None:
        if not self._standbys:
            return
        try:
            dump, newest = self._tsdb_export_cb(self._tsdb_cursor)
        except Exception:  # noqa: BLE001 - telemetry best-effort
            logger.exception("tsdb export failed")
            return
        if not dump:
            return
        self._tsdb_cursor = newest
        self._send_all({"kind": "repl_tsdb", "rid": None,
                        "epoch": self.epoch, "series": dump})


# --------------------------------------------------------------- the standby
class StandbyHead:
    """Warm standby: stream the primary's ledger into live tables and
    promote to a serving :class:`GcsServer` the moment the primary dies.

    ``auto_promote`` (default True) promotes on stream loss with the
    endpoint verified dead (re-dial refused) or on missed heartbeats;
    :meth:`promote` forces it (e.g. planned head maintenance)."""

    def __init__(self, session, head_resources: Optional[dict] = None,
                 auto_promote: bool = True,
                 on_promote: Optional[Callable[[dict], None]] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG
        self.session = session
        self.head_resources = dict(head_resources or {})
        self.auto_promote = auto_promote
        self.on_promote = on_promote
        self._timeout = max(0.2, GLOBAL_CONFIG.gcs_standby_timeout_s)
        self._lock = threading.Lock()  # no-block leaf (REPL_LOCK_DAG)
        self.state = new_ledger_state()      # guarded by: _lock
        self.applied_seq = 0                 # guarded by: _lock
        self.primary_epoch = 0               # guarded by: _lock
        self.synced = threading.Event()  # snapshot applied at least once
        self.promoted = None             # the GcsServer, once promoted
        self._promote_lock = threading.Lock()
        self._stop = threading.Event()
        self._conn = None
        # consecutive attaches dropped before any frame (stream-thread
        # owned): the no-hub-primary diagnostic counter
        self._attach_refused = 0
        self._unsynced_warned = False  # stream-thread owned
        self._tsdb = None
        if GLOBAL_CONFIG.metrics_enabled and GLOBAL_CONFIG.tsdb_enabled:
            from ray_tpu.util.tsdb import TSDB
            self._tsdb = TSDB()
        self._thread = threading.Thread(target=self._stream_loop,
                                        name="standby-stream", daemon=True)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "StandbyHead":
        # pre-warm the promote path: the heavy imports (gcs + the
        # native store extension) load NOW, while the primary is
        # healthy, so promote() pays construction only — import time
        # must not sit inside the failover window
        try:
            from ray_tpu._private import gcs as _gcs  # noqa: F401
            from ray_tpu._private.config import GLOBAL_CONFIG
            if GLOBAL_CONFIG.use_native_store:
                from ray_tpu.native import SlabStore  # noqa: F401
        except Exception:  # noqa: BLE001 - no native toolchain: the
            # promote path probes the same ladder and degrades the same
            pass
        self._thread.start()
        return self

    def shutdown(self) -> None:
        """Clean stop WITHOUT promoting (conn + thread discharged — the
        runtime resource oracle asserts this path leaks nothing)."""
        self._stop.set()
        conn = self._conn
        if conn is not None:
            protocol.shutdown_conn(conn)  # wake a blocked recv
        self._thread.join(timeout=5.0)
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def wait_synced(self, timeout: float = 30.0) -> bool:
        return self.synced.wait(timeout)

    def caught_up_to(self, seq: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.applied_seq >= seq and self.synced.is_set():
                    return True
            time.sleep(0.01)
        return False

    def _copy_state_locked(self) -> Dict[str, Any]:
        """_lock held: per-table deep-enough copy of the applied
        tables (inner dicts copied — the stream thread mutates them)."""
        return {
            "kv": {ns: dict(t) for ns, t in self.state["kv"].items()},
            "functions": dict(self.state["functions"]),
            "named_actors": dict(self.state["named_actors"]),
            "actors": {a: dict(r)
                       for a, r in self.state["actors"].items()},
            "pgs": {p: dict(r) for p, r in self.state["pgs"].items()},
            "shm_objects": dict(self.state["shm_objects"]),
            "driver_ids": set(self.state["driver_ids"]),
        }

    def snapshot_state(self) -> Dict[str, Any]:
        """Deep-enough copy of the applied tables (the equivalence
        oracle compares this against the primary's capture)."""
        with self._lock:
            return self._copy_state_locked()

    # ------------------------------------------------------------ streaming
    def _gcs_path(self) -> str:
        return self.session.socket_path("gcs.sock")

    def _dial(self, attach: bool = True):
        """One negotiated replication conn; raises on a dead endpoint
        (dial errors propagate) or :class:`ReplUnsupported` when the
        primary cannot speak the replication protocol.  ``attach=False``
        stops after version negotiation (liveness probe): the primary
        never sees a ``repl_attach``, so it does not capture + ship its
        whole durable state into a conn about to close."""
        conn = protocol.connect(self._gcs_path())
        try:
            ch = protocol.RpcChannel(conn)
            ver = ch.negotiate()
            if ver < wire.PROTO_REPL:
                raise ReplUnsupported(
                    f"primary speaks v{ver} < v{wire.PROTO_REPL}")
            if attach:
                wire.conn_send(conn, {"kind": "repl_attach", "rid": None},
                               ver)
            return conn
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise

    def _stream_loop(self) -> None:
        # pre-snapshot record buffer: a repl_wal racing the bootstrap
        # snapshot ahead of it applies once the snapshot lands
        while not self._stop.is_set():
            try:
                conn = self._dial()
            except ReplUnsupported as e:
                logger.warning("cannot replicate: %s", e)
                if self._stop.wait(1.0):
                    return
                continue
            except (OSError, ConnectionError, EOFError, ValueError):
                if self._primary_down("dial refused"):
                    return
                continue
            self._conn = conn
            pre_buf: List[Tuple[int, Tuple]] = []
            have_snapshot = False
            saw_frame = False
            while not self._stop.is_set():
                try:
                    with lock_watchdog.bounded_block(
                            "repl.stream_poll", bound=self._timeout):
                        alive = conn.poll(self._timeout)
                    if not alive:
                        raise EOFError("replication heartbeat timeout")
                    # rtlint: blocks-ok(the poll gate above proved a
                    # frame is buffered — hub heartbeats every
                    # gcs_repl_heartbeat_s, so self._timeout bounds the
                    # poll and the recv drains without parking)
                    msg, _ = wire.conn_recv(conn)
                    saw_frame = True
                    self._attach_refused = 0
                except (EOFError, OSError, wire.WireError):
                    break
                kind = msg.get("kind")
                if kind == "repl_snapshot":
                    self._apply_snapshot(msg, pre_buf)
                    have_snapshot = True
                    pre_buf = []
                elif kind == "repl_wal":
                    if have_snapshot:
                        self._apply_records(msg.get("records", ()))
                    else:
                        pre_buf.extend(msg.get("records", ()))
                elif kind == "repl_tsdb":
                    if self._tsdb is not None:
                        self._tsdb.seed(msg.get("series", ()))
                elif kind == "repl_heartbeat":
                    pass  # poll-timeout reset is the liveness signal
                else:
                    logger.warning("unknown replication frame %r", kind)
            self._conn = None
            try:
                conn.close()
            except OSError:
                pass
            if self._stop.is_set():
                return
            if not saw_frame:
                # the server accepted the dial but dropped the conn
                # before ANY frame: it has no replication hub (e.g.
                # gcs_wal=False) — negotiation alone can't tell us.
                # Surface it loudly and back off instead of hot-looping
                # a dial the probe would keep calling "alive".
                self._attach_refused += 1
                if self._attach_refused == 3:
                    logger.error(
                        "primary repeatedly dropped repl_attach before "
                        "sending any frame — is it running with "
                        "gcs_wal=False?  Standing by without a stream "
                        "(will keep retrying slowly).")
                if self._stop.wait(2.0 if self._attach_refused >= 3
                                   else 0.2):
                    return
                continue
            if self._primary_down("stream EOF"):
                return

    def _apply_snapshot(self, msg: dict, pre_buf) -> None:
        state = msg.get("state") or {}
        with self._lock:
            self.state = new_ledger_state()
            for key in self.state:
                if key in state:
                    val = state[key]
                    self.state[key] = (set(val) if key == "driver_ids"
                                       else dict(val))
            self.applied_seq = int(msg.get("wal_seq") or 0)
            self.primary_epoch = int(msg.get("epoch") or 0)
            for seq, op in pre_buf:
                if seq > self.applied_seq:
                    try:
                        apply_op(self.state, tuple(op))
                    except Exception:  # noqa: BLE001
                        logger.exception("standby op apply failed")
                    self.applied_seq = max(self.applied_seq, int(seq))
        self.synced.set()
        logger.info("standby synced: epoch %d seq %d",
                    self.primary_epoch, self.applied_seq)

    def _apply_records(self, records) -> None:
        with self._lock:
            for seq, op in records:
                if seq <= self.applied_seq:
                    continue  # idempotent replay / duplicate delivery
                try:
                    apply_op(self.state, tuple(op))
                except Exception:  # noqa: BLE001 - one bad op must not
                    # desync the standby from the stream position
                    logger.exception("standby op apply failed")
                self.applied_seq = int(seq)

    def _probe_endpoint(self) -> bool:
        """True when the primary endpoint answers a negotiate (the probe
        conn is closed immediately; no ``repl_attach`` is sent)."""
        try:
            probe = self._dial(attach=False)
        except ReplUnsupported:
            return True  # alive, just can't replicate — not a death
        except (OSError, ConnectionError, EOFError, ValueError):
            return False
        try:
            return True
        finally:
            probe.close()

    def _primary_down(self, why: str) -> bool:
        """The stream broke.  Distinguish a transient break from primary
        death with one quick re-dial probe; promote (or keep retrying)
        accordingly.  Returns True when this thread should exit."""
        if not self.synced.is_set():
            # never synced: nothing to promote from — keep dialing (a
            # restarted primary lets us bootstrap; loudly, because an
            # operator who armed this standby believes failover works)
            if not self._unsynced_warned:
                self._unsynced_warned = True
                logger.warning(
                    "primary lost (%s) BEFORE the first snapshot sync: "
                    "nothing to promote from — waiting for an endpoint",
                    why)
            if self._stop.wait(0.2):
                return True
            return False
        alive = self._probe_endpoint()
        if alive:
            # endpoint alive (maybe a restarted primary): re-bootstrap
            # on a fresh conn by returning to the stream loop's dial
            if self._stop.wait(0.05):
                return True
            return False
        if not self.auto_promote:
            logger.warning("primary down (%s); auto-promote disabled",
                           why)
            return self._stop.wait(0.5)
        logger.warning("primary down (%s): promoting standby", why)
        try:
            self.promote()
        except Exception:  # noqa: BLE001 - a failed promote must be
            # loud; the operator can still boot a head manually
            logger.exception("standby promotion FAILED")
        return True

    # ------------------------------------------------------------- promote
    def promote(self):
        """Promote to a serving head: write the applied tables as the
        durable snapshot (ledger-epoch-stamped so the dead primary's
        fsynced-but-unstreamed WAL tail replays on top), then boot a
        real GcsServer over the session dir — it claims the next ledger
        epoch (fencing any still-alive old primary), re-binds
        ``gcs.sock``, and serves; raylets and clients re-attach through
        their normal reconnect paths."""
        with self._promote_lock:
            if self.promoted is not None:
                return self.promoted
            t0 = time.monotonic()
            # Per-table deep copy AND the stream cursor in ONE _lock
            # hold: the stream thread may still be applying records
            # (explicit promote with a live primary) — pickling shared
            # inner dicts outside the lock would race their mutation,
            # and a cursor read from a later hold could claim coverage
            # of records the copied tables don't contain.
            with self._lock:
                state = self._copy_state_locked()
                state["wal_seq"] = self.applied_seq
                state["ledger_epoch"] = self.primary_epoch
            snap = gcs_state_dir(self.session.path) / "snapshot.pkl"
            write_snapshot_file(snap, state)
            from ray_tpu._private.gcs import GcsServer
            srv = GcsServer(self.session, self.head_resources)
            if self._tsdb is not None and srv._tsdb is not None:
                try:
                    dump, _ = self._tsdb.export_since(0.0)
                    srv._tsdb.seed(dump)
                except Exception:  # noqa: BLE001 - history is telemetry
                    logger.exception("tsdb handoff failed")
            self.promoted = srv
            took = time.monotonic() - t0
            logger.warning("standby promoted in %.0fms (epoch %d, seq "
                           "%d)", took * 1e3, srv.ledger_epoch,
                           state["wal_seq"])
            if self.on_promote is not None:
                try:
                    self.on_promote({"promote_s": took,
                                     "epoch": srv.ledger_epoch,
                                     "wal_seq": state["wal_seq"],
                                     "ts": time.time()})
                except Exception:  # noqa: BLE001
                    logger.exception("on_promote callback failed")
            return srv


# ------------------------------------------------------------------ CLI
def _main(argv=None) -> int:
    """``python -m ray_tpu._private.replication --session DIR``: run a
    warm standby for an existing session; on primary death it promotes
    in-process and keeps serving until SIGTERM."""
    import argparse
    import json
    import signal
    import sys

    ap = argparse.ArgumentParser(prog="ray_tpu-standby")
    ap.add_argument("--session", required=True,
                    help="session directory of the primary head")
    ap.add_argument("--num-cpus", type=float, default=0.0,
                    help="head CPU resource if promoted (0 = host cpus)")
    ap.add_argument("--timings", default="",
                    help="write promote timings JSON here on promotion")
    ap.add_argument("--no-auto-promote", action="store_true")
    args = ap.parse_args(argv)

    from ray_tpu._private import resource_sanitizer
    from ray_tpu._private.session import Session
    resource_sanitizer.maybe_install()
    # the warm standby samples itself too (DESIGN.md §4o): its history
    # becomes visible through the store the moment it promotes to head
    from ray_tpu.util import profiler as profiler_mod
    profiler_mod.maybe_install("standby")
    root, name = os.path.split(os.path.abspath(args.session))
    session = Session(root=root, name=name)
    protocol.set_authkey(session.auth_key())
    resources = {"CPU": args.num_cpus} if args.num_cpus else {}

    def on_promote(rec: dict) -> None:
        if args.timings:
            tmp = args.timings + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, args.timings)

    standby = StandbyHead(session, head_resources=resources,
                          auto_promote=not args.no_auto_promote,
                          on_promote=on_promote).start()
    print("STANDBY_READY", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    synced_announced = False
    while not stop.wait(0.2):
        if not synced_announced and standby.synced.is_set():
            # the arm signal harnesses/operators wait for: before this
            # line a primary death has nothing to promote from
            synced_announced = True
            print("STANDBY_SYNCED", flush=True)
        if standby.promoted is None and not standby._thread.is_alive():
            # stream thread exited without promoting (failed promote or
            # never synced + stop): nothing left to do
            break
    if standby.promoted is not None:
        standby.promoted.shutdown()  # asserts sanitizer-clean
    else:
        standby.shutdown()
        resource_sanitizer.assert_clean_at_shutdown("standby-shutdown")
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    raise SystemExit(_main())
