"""Actor-side method server.

Reference: the actor path of ``CoreWorker`` task receiving
(``ActorTaskSubmitter`` peer; SURVEY.md §3.3): callers connect directly to
the actor's worker, calls execute in arrival order (single-threaded by
default; ``max_concurrency>1`` → thread pool; async methods run on a
dedicated event loop), results go back on the caller connection (fast path)
and are sealed with the GCS (authoritative path) so any process can get them.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import queue
import threading
import time
from typing import Any, List, Optional

from ray_tpu._private import protocol, rtlog
from ray_tpu.util import metrics_catalog as mcat
from ray_tpu.util import tracing
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.serialization import serialize_to_bytes
from ray_tpu import exceptions as exc

logger = rtlog.get("actor")


class ActorExit(SystemExit):
    """Raised by exit_actor() inside a method to terminate gracefully."""


class ActorServer:
    def __init__(self, worker, spec: dict, instance: Any):
        self.worker = worker
        self.spec = spec
        self.instance = instance
        self.actor_id = spec["actor_id"]
        self.max_concurrency = int(spec.get("max_concurrency") or 1)
        if worker.session is None:
            # remote-agent host: no shared session dir, and a unix socket
            # would be unreachable from other hosts — listen on an
            # ephemeral TCP port and advertise this host's address
            # (RTPU_ADVERTISE_HOST, set by the NodeAgent)
            self._listener = protocol.make_tcp_actor_listener()
            host = os.environ.get("RTPU_ADVERTISE_HOST", "127.0.0.1")
            self.addr = f"tcp://{host}:{self._listener.address[1]}"
        else:
            sock_name = f"a_{self.actor_id[:12]}_{os.getpid()}.sock"
            self.addr = worker.session.socket_path(sock_name)
            self._listener = protocol.make_listener(self.addr)
        try:
            self._queue: "queue.Queue" = queue.Queue()
            self._send_lock = threading.Lock()  # replies come from executor
            # threads AND the asyncio loop; Connection.send isn't
            # thread-safe.
            # Serial actors (max_concurrency=1) execute calls directly on
            # the connection-reader thread under _exec_lock instead of
            # hopping through the queue to the executor thread: one fewer
            # thread handoff per call (~2 GIL wakeups) on the serial-RT
            # hot path.  The lock preserves the one-call-at-a-time
            # contract across multiple caller connections exactly as the
            # single executor thread did.
            self._exec_lock = threading.Lock()
            self._direct_exec = self.max_concurrency == 1
            self._stopped = threading.Event()
            self._loop: Optional[asyncio.AbstractEventLoop] = None
            if any(inspect.iscoroutinefunction(
                    getattr(type(instance), m, None))
                   for m in dir(type(instance))):
                self._loop = asyncio.new_event_loop()
                threading.Thread(target=self._loop.run_forever,
                                 name="actor-asyncio", daemon=True).start()
            threading.Thread(target=self._accept_loop, name="actor-accept",
                             daemon=True).start()
        except BaseException:
            # a failed boot returns no server: the caller cannot close
            # the listener it never received (an actor-creation retry
            # would otherwise leak one bound port/socket per attempt)
            self._listener.close()
            raise

    # ------------------------------------------------------------- transport
    def _accept_loop(self) -> None:
        # TCP listeners are internet-facing on remote-agent hosts, so
        # half-open probes and port scans hit this accept path routinely.
        protocol.serve_accept_loop(self._listener, self._stopped.is_set,
                                   self._conn_reader, "actor-conn-reader")

    def _conn_reader(self, conn) -> None:
        try:
            while not self._stopped.is_set():
                try:
                    # rtlint: blocks-ok(parks between a caller's method
                    # invocations; caller death EOFs the conn — peer
                    # liveness is the deadline, per-conn thread)
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if not self._direct_exec:
                    self._queue.put((conn, msg))
                    continue
                try:
                    with self._exec_lock:
                        self._handle_call(conn, msg)
                except ActorExit:
                    self._shutdown()
                    return
                except Exception:  # noqa: BLE001
                    # _handle_call replies its own errors, so reaching
                    # here means the REPLY machinery failed and the
                    # conn's framing state is unknown: tear it down so
                    # the caller sees EOF (→ actor-error/resubmit path),
                    # never an infinite hang on a swallowed dispatch
                    logger.exception("actor call handling failed")
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        if self.max_concurrency > 1:
            threads = [threading.Thread(target=self._exec_loop, daemon=True,
                                        name=f"actor-exec-{i}")
                       for i in range(self.max_concurrency - 1)]
            for t in threads:
                t.start()
        self._exec_loop()

    def _exec_loop(self) -> None:
        while not self._stopped.is_set():
            # rtlint: blocks-ok(parks until work arrives; _shutdown
            # enqueues a None sentinel per exec thread, so stop always
            # wakes the get — the sentinel is the deadline)
            item = self._queue.get()
            if item is None:
                return
            conn, msg = item
            try:
                self._handle_call(conn, msg)
            except ActorExit:
                self._shutdown()
                return
            except Exception:  # noqa: BLE001
                # reply machinery failed (handlers reply their own
                # errors): EOF the caller instead of stranding it
                logger.exception("actor call handling failed")
                try:
                    conn.close()
                except OSError:
                    pass

    # -------------------------------------------------------------- execution
    def _run_method(self, method_name: str, args: list, kwargs: dict) -> Any:
        if method_name == "__ray_terminate__":
            raise ActorExit(0)
        if method_name == "__ray_ready__":
            return True
        if method_name == "__ray_apply__":
            # Run an arbitrary function against the actor instance (reference:
            # ``__ray_call__``): fn(instance, *args, **kwargs).  Used by the
            # collective layer and Train's WorkerGroup to execute code inside
            # an existing actor without the user declaring a method for it.
            fn, *rest = args
            return fn(self.instance, *rest, **kwargs)
        method = getattr(self.instance, method_name)
        if inspect.iscoroutinefunction(method):
            if self._loop is None:
                return asyncio.run(method(*args, **kwargs))
            # handled by _handle_call's async fast path; reaching here means
            # a coroutine method was invoked via __ray_apply__ — block, as
            # there is no conn to reply on later
            fut = asyncio.run_coroutine_threadsafe(
                method(*args, **kwargs), self._loop)
            return fut.result()
        return method(*args, **kwargs)

    async def _run_async_call(self, method, args, kwargs, conn, msg) -> None:
        """Body of an async method call: only the await runs ON the event
        loop (no executor thread parked while the coroutine waits); result
        serialization, sealing, and the reply — all blocking I/O — are
        handed back to a thread so parked coroutines never stall behind
        them.  BaseException (incl. ActorExit) must be caught here: an
        unobserved exception in the loop future would hang the caller."""
        value = err = None
        try:
            value = await method(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            err = e
        asyncio.get_running_loop().run_in_executor(
            None, self._complete_async_call, conn, msg, value, err)

    def _observe_call(self, msg: dict, t0: Optional[float]) -> None:
        """Actor methods feed the same exec histogram as plain tasks,
        tagged ``Class.method`` — one series family for 'where did the
        worker's time go' across both execution paths.  Control-plane
        methods (``__ray_ready__``, ``__ray_terminate__``, ...) are
        excluded: their durations measure bring-up/teardown round-trips,
        not user work, and would add a control series per class."""
        method = msg.get("method", "?")
        if t0 is None or method.startswith("__ray_") \
                or not GLOBAL_CONFIG.metrics_enabled:
            return
        mcat.get("rtpu_task_exec_seconds").observe(
            time.monotonic() - t0,
            tags={"name": f"{self.spec.get('class_name', 'Actor')}."
                          f"{method}"})

    def _complete_async_call(self, conn, msg, value, err) -> None:
        return_ids: List[str] = msg["return_ids"]
        w = self.worker
        try:
            try:
                self._observe_call(msg, msg.pop("_exec_t0", None))
                actx = msg.pop("_span_ctx", None)
                at0 = msg.pop("_span_t0", None)
                if actx is not None and at0 is not None:
                    tracing.emit_ctx_span(
                        actx,
                        f"{self.spec.get('class_name', 'Actor')}."
                        f"{msg.get('method', '?')}",
                        at0, time.time() - at0, cat="actor_task")
                if err is None:
                    try:
                        results = w._store_results(return_ids, value,
                                                   msg["num_returns"])
                        ok = True
                    except Exception as store_err:  # noqa: BLE001 - e.g.
                        # unpicklable result: the caller must still get a
                        # reply
                        err = store_err
                if err is not None:
                    if isinstance(err, ActorExit):
                        wrapped: BaseException = exc.RayActorError(
                            self.actor_id, "actor exited")
                    else:
                        wrapped = exc.RayTaskError.from_exception(
                            f"{self.spec.get('class_name', 'Actor')}."
                            f"{msg['method']}", err)
                    err_res = {"loc": "error",
                               "data": serialize_to_bytes(wrapped)[0]}
                    results = [err_res for _ in return_ids]
                    ok = False
                self._seal_and_reply(conn, msg, results, ok)
            except Exception:  # noqa: BLE001 - reply machinery failed.
                # This runs as a loop-submitted executor job: an escaping
                # exception lands in an unobserved Future — the caller
                # would hang forever.  EOF it instead.
                logger.exception("async actor call completion failed")
                try:
                    conn.close()
                except OSError:
                    pass
        finally:
            if isinstance(err, ActorExit):
                self._shutdown()

    def _handle_call(self, conn, msg: dict) -> None:
        return_ids: List[str] = msg["return_ids"]
        num_returns = msg["num_returns"]
        w = self.worker
        if msg.get("_resubmitted") and return_ids:
            # A resubmitted call may have COMPLETED on the previous
            # incarnation (results seal with the GCS before the inline
            # reply; death can race the reply).  The caller's own dedup
            # can miss seal events still in flight at disconnect time —
            # by the time the restarted actor executes, the GCS has
            # drained them, so this check is authoritative.  Prevents
            # re-executing finished methods on stateful actors.
            try:
                metas = w.rpc("peek_meta",
                              object_ids=return_ids).get("metas", {})
                if all(m and m.get("state") in ("ready", "error")
                       for m in metas.values()):
                    with self._send_lock:
                        conn.send({"call_id": msg["call_id"],
                                   "return_ids": return_ids,
                                   "inline_results": [None] * len(return_ids),
                                   "ok": True})
                    return
            except (OSError, EOFError):
                pass  # control plane hiccup: at-least-once fallback
        t_exec = time.monotonic()
        from ray_tpu._private import flight_recorder
        if flight_recorder.enabled():
            flight_recorder.record(
                "actor_call",
                f"{self.spec.get('class_name', 'Actor')}."
                f"{msg.get('method', '?')}")
        try:
            args, kwargs = w._unpack_args(msg)
            method_name = msg["method"]
            if self._loop is not None and method_name not in (
                    "__ray_terminate__", "__ray_ready__", "__ray_apply__"):
                method = getattr(self.instance, method_name, None)
                if method is not None and inspect.iscoroutinefunction(method):
                    msg["_exec_t0"] = t_exec
                    # span context flows into the coroutine: adopt the
                    # child span on THIS thread — run_coroutine_threadsafe
                    # captures the caller's contextvars — and stash it on
                    # the msg so _complete_async_call emits the event
                    aspan = tracing.SpanContext.from_dict(
                        msg.get("trace_ctx"))
                    tok = None
                    if aspan is not None:
                        ctx = tracing.child_span(aspan, method_name)
                        msg["_span_ctx"] = ctx
                        msg["_span_t0"] = time.time()
                        tok = tracing.adopt(ctx)
                    try:
                        asyncio.run_coroutine_threadsafe(
                            self._run_async_call(method, args, kwargs,
                                                 conn, msg),
                            self._loop)
                    finally:
                        if tok is not None:
                            tracing.restore(tok)
                    # executor thread freed; the reply obligation moves to
                    # the event loop (_run_async_call → _complete_async_call
                    # replies or tears the conn down on every path)
                    # rtlint: reply-missing-ok(deferred reply via event loop)
                    return
            span = tracing.SpanContext.from_dict(msg.get("trace_ctx"))
            if span is not None:
                # child span per method call; timeline events link back to
                # the caller's span (reference: ray.util.tracing).  The
                # event carries the SAME span id the method body saw, so
                # spans opened inside (engine submits, nested calls)
                # parent correctly; rows use the stable per-thread tid +
                # thread_name metadata (emit_ctx_span).
                t0 = time.time()
                tracing._set_span(tracing.child_span(span, method_name))
            try:
                value = self._run_method(method_name, args, kwargs)
            finally:
                if span is not None:
                    tracing.emit_ctx_span(
                        tracing.current_span(),
                        f"{self.spec.get('class_name', 'Actor')}."
                        f"{method_name}",
                        t0, time.time() - t0, cat="actor_task")
                    tracing._set_span(None)
            results = w._store_results(return_ids, value, num_returns)
            ok = True
        except ActorExit:
            err_res = {"loc": "error",
                       "data": serialize_to_bytes(
                           exc.RayActorError(self.actor_id, "actor exited"))[0]}
            results = [err_res for _ in return_ids]
            ok = False
            self._seal_and_reply(conn, msg, results, ok)
            raise
        except Exception as e:  # noqa: BLE001
            err = exc.RayTaskError.from_exception(
                f"{self.spec.get('class_name', 'Actor')}.{msg['method']}", e)
            err_res = {"loc": "error", "data": serialize_to_bytes(err)[0]}
            results = [err_res for _ in return_ids]
            ok = False
        self._observe_call(msg, t_exec)
        self._seal_and_reply(conn, msg, results, ok)

    def _seal_and_reply(self, conn, msg: dict, results: List[dict],
                        ok: bool) -> None:  # rtlint: replies
        w = self.worker
        # authoritative: seal with GCS (one-way on the worker's task channel)
        w._send_event({"kind": "actor_result", "return_ids": msg["return_ids"],
                       "results": results})
        # release the caller's in-flight arg pins
        if msg.get("arg_ledger"):
            w.rpc_oneway("release_all", ledger=msg["arg_ledger"])
        # fast path: inline values straight back to the caller (errors go via
        # the GCS so the caller's local cache never masks a raise)
        inline = [r.get("data") if r["loc"] == "inline" else None
                  for r in results]
        try:
            with self._send_lock:
                conn.send({"call_id": msg["call_id"],
                           "return_ids": msg["return_ids"],
                           "inline_results": inline, "ok": ok})
        except (OSError, ValueError):
            pass  # caller went away; results are in the GCS regardless

    def stop_serving(self) -> None:
        """Stop the server WITHOUT declaring an intentional exit: the
        ray_tpu.kill path for proc-less (remote/raylet) actor workers —
        the control plane already recorded its own death reason and
        restart policy, and an actor_exit event here would wrongly
        suppress a no_restart=False restart."""
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        # unblock sibling exec threads
        for _ in range(self.max_concurrency):
            self._queue.put(None)

    def _shutdown(self) -> None:
        # tell the control plane this exit is intentional → no restart
        self.worker._send_event({"kind": "actor_exit", "actor_id": self.actor_id})
        self.stop_serving()


def exit_actor() -> None:
    """Terminate the current actor gracefully (reference: ray.actor.exit_actor)."""
    raise ActorExit(0)
