"""ID types for objects, tasks, actors, jobs, nodes, placement groups.

Reference: ``src/ray/common/id.h`` (SURVEY.md §2.1) — Ray ObjectIDs embed the
owner (task) id plus a return/put index so ownership is derivable from the id
alone.  We keep that property: an ``ObjectID`` is
``<owner_worker_hex16><kind:1><counter_hex10>`` so any process can read the
owner straight off the id without a directory lookup.
"""

from __future__ import annotations

import os
import threading
import uuid


# 16-char ID generation is on the task-submission hot path (one TaskID
# per `.remote()`), and uuid4/urandom cost 20-30µs per call on small
# hosts (one getrandom syscall each).  Instead: one 40-bit urandom
# prefix per process plus a 24-bit counter — unique within a process by
# the counter, across processes by the prefix (birthday risk over 1k
# processes ≈ 5e-7), re-seeded on counter rollover and after fork
# (os.getpid check) so forked children never continue the parent's
# sequence.  Short ids (worker/job — rare, per-process not per-task)
# keep full per-call entropy.
_seed_lock = threading.Lock()
_seed = ["", 0, 0]  # [prefix_hex10, counter, pid]


def _rand_hex(n: int) -> str:
    if n < 16:
        return uuid.uuid4().hex[:n]
    with _seed_lock:
        pid = os.getpid()
        if _seed[2] != pid or _seed[1] >= 0xFFFFFF:
            _seed[0] = os.urandom(5).hex()
            _seed[1] = 0
            _seed[2] = pid
        _seed[1] += 1
        h = f"{_seed[0]}{_seed[1]:06x}"
    return h if n == 16 else h + uuid.uuid4().hex[:n - 16]


class _Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._n = 0

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


class WorkerID(str):
    @classmethod
    def new(cls) -> "WorkerID":
        # pid folded in for human debuggability of logs/ids.
        return cls(f"{os.getpid():08x}{_rand_hex(8)}")


class JobID(str):
    @classmethod
    def new(cls) -> "JobID":
        return cls(_rand_hex(8))


class NodeID(str):
    @classmethod
    def new(cls) -> "NodeID":
        return cls(_rand_hex(16))


class TaskID(str):
    @classmethod
    def new(cls) -> "TaskID":
        return cls(_rand_hex(16))


class ActorID(str):
    @classmethod
    def new(cls) -> "ActorID":
        return cls(_rand_hex(16))


class PlacementGroupID(str):
    @classmethod
    def new(cls) -> "PlacementGroupID":
        return cls(_rand_hex(16))


KIND_PUT = "p"
KIND_RETURN = "r"


class ObjectID(str):
    """``<owner16><kind1><counter10>`` — owner-embedding object id."""

    @classmethod
    def make(cls, owner: str, kind: str, counter: int) -> "ObjectID":
        assert kind in (KIND_PUT, KIND_RETURN)
        return cls(f"{owner[:16]:>16s}{kind}{counter:010x}")

    @property
    def owner(self) -> str:
        return self[:16]

    @property
    def is_put(self) -> bool:
        return self[16] == KIND_PUT
