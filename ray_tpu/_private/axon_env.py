"""Single source of truth for scrubbing the TPU-tunnel environment.

The ambient environment on TPU-tunnel hosts pins ``JAX_PLATFORMS`` to the
tunnel's PJRT plugin and pre-registers it via a ``sitecustomize.py`` on
``PYTHONPATH``; any process that imports jax with those vars set claims the
real chip (and pays a multi-second plugin init, or blocks if the chip is
already claimed).  Three places need the same scrub — the test rig
(``tests/conftest.py``), CPU worker spawns (``_private/gcs.py``), and the
driver's multi-chip dryrun (``__graft_entry__.py``) — so it lives here, with
no jax (or heavy ray_tpu) imports of its own.
"""

from __future__ import annotations

import os
from typing import MutableMapping, Optional

# Every env var the tunnel's sitecustomize reacts to.  Popping only the
# pool-IPs var is enough to skip plugin *registration*, but the others leak
# tunnel behavior into children that re-set it, so drop the whole set.
AXON_ENV_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "AXON_POOL_SVC_OVERRIDE",
    "AXON_LOOPBACK_RELAY",
    "PALLAS_AXON_REMOTE_COMPILE",
    "PALLAS_AXON_TPU_GEN",
    "TPU_WORKER_HOSTNAMES",
)


def _is_tunnel_site_dir(path: str) -> bool:
    """True for the tunnel's sitecustomize dir specifically (it holds both a
    ``sitecustomize.py`` and the plugin package) — NOT any path merely
    containing the substring "axon", which would strip unrelated user
    packages from PYTHONPATH."""
    return (os.path.isfile(os.path.join(path, "sitecustomize.py"))
            and os.path.isdir(os.path.join(path, "axon")))


def tpu_tunnel_present(env: Optional[MutableMapping] = None) -> bool:
    """True when the ambient env routes jax to the real-TPU tunnel."""
    env = os.environ if env is None else env
    return bool(env.get("PALLAS_AXON_POOL_IPS"))


def scrub_tpu_tunnel(
    env: MutableMapping,
    *,
    cpu_devices: Optional[int] = None,
    drop_plugin_pythonpath: bool = False,
) -> MutableMapping:
    """Mutate ``env`` so a process seeing it runs jax on the CPU backend.

    ``env`` may be ``os.environ`` (scrub the current process before jax is
    imported) or a child-process env dict.

    - ``cpu_devices``: if set, force that many virtual CPU host devices via
      ``XLA_FLAGS`` (replacing any existing force-count flag).
    - ``drop_plugin_pythonpath``: also remove the sitecustomize dir from
      ``PYTHONPATH`` so even the plugin *registration hook* never runs
      (needed when the child must not pay the plugin import at all).
    """
    for k in AXON_ENV_VARS:
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    if drop_plugin_pythonpath:
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and not _is_tunnel_site_dir(p)]
        env["PYTHONPATH"] = os.pathsep.join(parts)
    if cpu_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={cpu_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env
