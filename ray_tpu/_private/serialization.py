"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Reference: ``python/ray/_private/serialization.py`` + vendored cloudpickle
(SURVEY.md §2.3) — closures serialized by value; large contiguous buffers
(numpy / jax host arrays) travel out-of-band so reads are zero-copy views
onto shared memory; ``ObjectRef``s found inside values are surfaced so the
control plane can track borrowed references.

Wire layout of a stored object::

    [8B magic+version][8B pickle_len][8B nbuf]
    [nbuf * 16B (offset,len) table]
    [pickle bytes][padding to 64][buf0 .. bufN  each 64-aligned]

64-byte alignment keeps numpy views cache-line aligned (and XLA host-buffer
friendly for the dlpack staging path).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, Dict, List, Tuple, Type

import cloudpickle

_MAGIC = b"RTPUOBJ1"
_ALIGN = 64
_HDR = struct.Struct("<8sQQ")
_ENT = struct.Struct("<QQ")


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _RefCollector:
    """Pickler hook that records ObjectRefs serialized inside a value."""

    def __init__(self) -> None:
        self.refs: List[Any] = []

    def __call__(self, ref: Any) -> None:
        self.refs.append(ref)


# The custom-serializer registry (ray.util.register_serializer parity).
_CUSTOM: Dict[Type, Tuple[Callable, Callable]] = {}


def register_serializer(cls: Type, *, serializer: Callable, deserializer: Callable) -> None:
    _CUSTOM[cls] = (serializer, deserializer)


def deregister_serializer(cls: Type) -> None:
    _CUSTOM.pop(cls, None)


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, protocol, buffer_callback, ref_collector):
        super().__init__(file, protocol=protocol, buffer_callback=buffer_callback)
        self._ref_collector = ref_collector

    def persistent_id(self, obj):  # noqa: D401 - pickle hook
        return None

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef, _deserialize_object_ref
        if isinstance(obj, ObjectRef):
            if self._ref_collector is not None:
                self._ref_collector(obj)
            return (_deserialize_object_ref, (str(obj.id),))
        ser = _CUSTOM.get(type(obj))
        if ser is not None:
            serializer, deserializer = ser
            return (deserializer, (serializer(obj),))
        # Delegate to cloudpickle's reducer_override — that is where its
        # by-value class/function pickling lives; returning NotImplemented
        # here would skip it and local classes would fail to pickle.
        return super().reducer_override(obj)


_NONE_PICKLE: bytes = pickle.dumps(None, protocol=5)


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer], List[Any]]:
    """Returns (pickle_bytes, oob_buffers, contained_object_refs)."""
    if value is None:
        # the single most common task result (side-effect tasks): its
        # pickle is a constant — skip the pickler machinery entirely
        return _NONE_PICKLE, [], []
    buffers: List[pickle.PickleBuffer] = []
    collector = _RefCollector()
    f = io.BytesIO()
    p = _Pickler(f, protocol=5, buffer_callback=buffers.append,
                 ref_collector=collector)
    p.dump(value)
    return f.getvalue(), buffers, collector.refs


def serialized_size(pickled: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    n = _HDR.size + _ENT.size * len(buffers)
    n = _align(n + len(pickled))
    for b in buffers:
        n = _align(n + _raw_view(b).nbytes)
    return n


def _raw_view(b: pickle.PickleBuffer) -> memoryview:
    """Physical-order byte view of an out-of-band buffer.

    ``raw()`` handles F-contiguous arrays (plain ``cast('B')`` is restricted
    to C-contiguous views); unpickling rebuilds from the same physical order.
    """
    try:
        return b.raw()
    except BufferError:
        v = memoryview(b)
        return v if (v.ndim == 1 and v.format == "B") else memoryview(bytes(v))


def write_to(buf: memoryview, pickled: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    """Write the wire layout into ``buf``; returns bytes written."""
    views = [_raw_view(b) for b in buffers]
    off = _HDR.size + _ENT.size * len(views)
    pickle_off = off
    off = _align(off + len(pickled))
    entries = []
    for v in views:
        entries.append((off, v.nbytes))
        off = _align(off + v.nbytes)
    _HDR.pack_into(buf, 0, _MAGIC, len(pickled), len(views))
    pos = _HDR.size
    for e in entries:
        _ENT.pack_into(buf, pos, *e)
        pos += _ENT.size
    buf[pickle_off:pickle_off + len(pickled)] = pickled
    for (boff, blen), v in zip(entries, views):
        buf[boff:boff + blen] = v
    return off


def to_wire_bytes(pickled: bytes,
                  buffers: List[pickle.PickleBuffer]) -> bytearray:
    """Assemble the wire layout in memory (for inline/slab objects)."""
    out = bytearray(serialized_size(pickled, buffers))
    write_to(memoryview(out), pickled, buffers)
    return out


def serialize_to_bytes(value: Any) -> Tuple[bytearray, List[Any]]:
    """One-shot: full wire-format bytes (for inline objects / socket
    transport).  Returns a bytearray — callers only need a bytes-like;
    an extra ``bytes()`` copy would double the cost of every large
    transfer."""
    pickled, buffers, refs = serialize(value)
    return to_wire_bytes(pickled, buffers), refs


def write_value_to_fd(fd: int, pickled: bytes,
                      buffers: List[pickle.PickleBuffer]) -> int:
    """Stream the wire layout straight to ``fd`` with writev — for the
    tmpfs segment plane, where write() beats mmap-and-memcpy ~2x (fresh
    pages fault once in the kernel instead of once per user-space touch).
    Returns bytes written.  One data copy total: buffers → page cache."""
    import os
    views = [_raw_view(b) for b in buffers]
    head_len = _HDR.size + _ENT.size * len(views)
    off = _align(head_len + len(pickled))
    entries = []
    for v in views:
        entries.append((off, v.nbytes))
        off = _align(off + v.nbytes)
    head = bytearray(_align(head_len + len(pickled)))
    _HDR.pack_into(head, 0, _MAGIC, len(pickled), len(views))
    pos = _HDR.size
    for e in entries:
        _ENT.pack_into(head, pos, *e)
        pos += _ENT.size
    head[head_len:head_len + len(pickled)] = pickled

    iov: List[memoryview] = [memoryview(head)]
    cursor = len(head)
    for (boff, blen), v in zip(entries, views):
        if boff > cursor:                     # alignment gap
            iov.append(memoryview(bytes(boff - cursor)))
            cursor = boff
        iov.append(v)
        cursor += blen
    if off > cursor:
        iov.append(memoryview(bytes(off - cursor)))

    total = 0
    while iov:
        n = os.writev(fd, iov[:1024])
        total += n
        # drop fully-written segments; re-slice a partial one
        while iov and n >= iov[0].nbytes:
            n -= iov[0].nbytes
            iov.pop(0)
        if iov and n:
            iov[0] = iov[0][n:]
    return total


def deserialize_from(buf: memoryview) -> Any:
    """Zero-copy deserialize: numpy arrays view ``buf`` directly.

    Caller must keep the backing mmap alive while views are alive (handled by
    ``ObjectRef`` pinning its ``MappedObject``).
    """
    magic, plen, nbuf = _HDR.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt ray_tpu object header")
    pos = _HDR.size
    entries = []
    for _ in range(nbuf):
        entries.append(_ENT.unpack_from(buf, pos))
        pos += _ENT.size
    pickled = bytes(buf[pos:pos + plen])
    oob = [pickle.PickleBuffer(buf[o:o + l]) for o, l in entries]
    return pickle.loads(pickled, buffers=oob)


def dumps_call(obj: Any) -> bytes:
    """Plain cloudpickle (functions, task specs over the control socket)."""
    return cloudpickle.dumps(obj, protocol=5)


def loads_call(data: bytes) -> Any:
    return pickle.loads(data)
