"""GCS: the cluster control plane.

Reference: ``src/ray/gcs/gcs_server/`` + the raylet's ``ClusterTaskManager``
(SURVEY.md §2.1, §3).  One GCS per cluster, owning:

- node table + health (``GcsNodeManager`` analog),
- the object directory + centralized refcounting (deviation from the
  reference's owner-based protocol, documented in DESIGN.md — owner ids are
  embedded in ObjectIDs so a later migration to owner-based counting does not
  change the API),
- task scheduling: hybrid/spread/affinity policies + worker-pool management
  (the reference splits this between GCS and per-node raylets; on one host a
  single scheduler with per-"node" logical resource views is equivalent and
  is how the reference's own ``cluster_utils.Cluster`` tests behave),
- actor lifecycle FSM (``GcsActorManager``: PENDING→ALIVE→RESTARTING→DEAD),
- placement groups with PACK/SPREAD/STRICT_* and TPU-topology bundles
  (``GcsPlacementGroupManager``),
- function/class table, KV store, named actors, job table,
- lineage for object reconstruction (reference keeps lineage at owners'
  ``TaskManager``; centralized here).

Threading model: listener accept loop + one handler thread per connection +
a worker-process monitor thread.  Locking (see DESIGN.md §4c for the full
discipline): scheduler/node/worker/actor/PG state AND object-table
*mutation* live under ``self.lock`` (+``self.cv``); hot-kind *reads* run on
fast paths that never take it — ``_sealed`` is a lock-free read table of
terminal object metas, object waiters live under ``_waiter_lock``, the KV
plane under ``_kv_lock``, timeline events under ``_events_lock``, and
refcount oneways are coalesced per connection and applied in batches under
one global-lock acquisition (``_drain_ref_ops``).  Lock order is strictly
``lock → {_waiter_lock | _kv_lock | _events_lock}``; the leaf locks never
nest inside each other and never acquire the global lock.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time
from collections import OrderedDict, defaultdict, deque
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private import protocol, rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.serialization import dumps_call
from ray_tpu._private.session import Session
from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.util import metrics_catalog as mcat
from ray_tpu.util.metrics import is_metrics_key
from ray_tpu.util.profiler import is_profile_key
from ray_tpu import exceptions as exc

logger = rtlog.get("gcs")

# object meta states
PENDING, READY, ERROR = "pending", "ready", "error"
# actor states (reference FSM)
A_PENDING, A_ALIVE, A_RESTARTING, A_DEAD = "PENDING", "ALIVE", "RESTARTING", "DEAD"


class NodeState:
    def __init__(self, node_id: str, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.resources_total = dict(resources)
        self.resources_avail = dict(resources)
        self.labels = labels or {}
        self.alive = True
        # lifecycle phase (DESIGN.md §4j): running -> draining (provider
        # preemption warning via ``node_draining``) -> terminating
        # (removal in progress).  Placement only targets ``running``
        # nodes; work already on a draining node keeps running until the
        # provider kills it.
        self.phase = "running"               # guarded by: lock
        self.drain_deadline: Optional[float] = None  # guarded by: lock
        self.drain_reason = ""               # guarded by: lock
        self.data_addr: Optional[str] = None  # P2P object-plane listener
        self.data_proto = 0  # holder's data-plane wire version (add_node)
        self.is_remote = False   # owned by a NodeAgent on another host:
        # the GCS cannot fork workers there (the agent owns the pool);
        # actors there listen on TCP and advertise tcp:// addresses
        self.workers: Set[str] = set()
        self.idle_workers: deque = deque()
        self.last_heartbeat = time.monotonic()
        # --- raylet lease channel (DESIGN.md §4i) ---
        # A node with a live raylet_conn is scheduled by GRANT: the pump
        # debits resources on this ledger and ships spec blocks down the
        # channel; the raylet dispatches locally and reports back in
        # batches.  leases_out is the ledger of granted-but-unsettled
        # specs — the unit of reclaim when the channel drops.
        self.raylet_conn = None          # guarded by: lock
        self.raylet_conn_lock = threading.Lock()
        self.raylet_proto = 0            # guarded by: lock
        self.raylet_epoch = 0            # guarded by: lock
        self.leases_out: Dict[str, dict] = {}   # guarded by: lock
        self.raylet_stats: dict = {}     # guarded by: lock
        self.raylet_reconcile_age = 0.0  # guarded by: lock

    def queued_lease_count(self) -> int:
        """Unfunded (``_lease_q``) leases outstanding on this node's
        raylet — the backlog-depth gate (lock held by callers)."""
        return sum(1 for s in self.leases_out.values()
                   if s.get("_lease_q"))

    def push_raylet(self, msg: dict) -> bool:
        """Push one lease frame to the node's raylet (wire-framed at the
        channel's negotiated version — never legacy pickle)."""
        from ray_tpu._private import wire
        with self.raylet_conn_lock:
            if self.raylet_conn is None:
                return False
            try:
                wire.conn_send(self.raylet_conn, msg, self.raylet_proto)
                return True
            except (OSError, ValueError):
                return False

    def load(self) -> float:
        cpu_t = self.resources_total.get("CPU", 0.0)
        if cpu_t <= 0:
            return 1.0
        return 1.0 - self.resources_avail.get("CPU", 0.0) / cpu_t

    def schedulable(self) -> bool:
        """Placement eligibility: alive AND not draining/terminating —
        a node under a preemption warning keeps its running work but
        never receives new tasks/leases/bundles (DESIGN.md §4j)."""
        return self.alive and self.phase == "running"

    def fits(self, req: Dict[str, float]) -> bool:
        return all(self.resources_avail.get(k, 0.0) >= v - 1e-9
                   for k, v in req.items() if v > 0)

    def acquire(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) - v

    def release_res(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) + v


class WorkerState:
    def __init__(self, worker_id: str, node_id: str, pid: int):
        self.worker_id = worker_id
        self.node_id = node_id
        self.pid = pid
        self.proc: Optional[subprocess.Popen] = None
        self.state = "starting"  # starting|idle|busy|actor|dead
        self.tpu_capable = False # spawned with device access (JAX sees TPU)
        self.task_conn = None    # Connection for pushes
        self.task_conn_lock = threading.Lock()
        # Out-of-band control channel (cancel / drop_queued / dump_stack /
        # stop_worker): with the worker executing tasks directly on its
        # task-conn reader thread (one fewer handoff per task), OOB
        # control must ride a second connection the worker's ctl thread
        # drains even mid-task.  Best-effort: absent (attach race,
        # reattach window) → fall back to the task conn.
        self.ctl_conn = None
        self.ctl_conn_lock = threading.Lock()
        self.blocked = False     # task currently parked in get() (CPU released)
        self.current_task: Optional[dict] = None
        # Lease pipelining (reference: lease reuse / worker lease caching):
        # same-shape tasks queue on the busy worker and ride its resource
        # lease; the worker's own task loop executes them in order, so the
        # per-task scheduler round trip overlaps with execution.
        self.pipeline: deque = deque()
        self.dseq = 0  # dispatch sequence for prepush revocation scoping
        self.actor_id: Optional[str] = None
        self.actor_addr: Optional[str] = None

    def push(self, msg: dict) -> bool:
        with self.task_conn_lock:
            if self.task_conn is None:
                return False
            try:
                self.task_conn.send(msg)
                return True
            except (OSError, ValueError):
                return False

    def push_ctl(self, msg: dict) -> bool:
        """Push an out-of-band control message (preferring the ctl conn so
        it is seen even while the worker's main thread executes a task)."""
        with self.ctl_conn_lock:
            conn = self.ctl_conn
            if conn is not None:
                try:
                    conn.send(msg)
                    return True
                except (OSError, ValueError):
                    self.ctl_conn = None
        return self.push(msg)


class ObjMeta:
    __slots__ = ("state", "loc", "data", "size", "node_id", "refcount",
                 "lineage_task", "contained", "has_producer")

    def __init__(self):
        self.state = PENDING
        self.loc = None          # inline|shm|spilled
        self.data: Optional[bytes] = None
        self.size = 0
        self.node_id: Optional[str] = None
        self.refcount = 0
        self.lineage_task: Optional[str] = None
        self.contained: List[str] = []  # refs nested inside the value
        # True while a submitted task's return is in flight: a PENDING
        # meta with a producer must survive refcount 0 (the seal is
        # coming); a PENDING meta WITHOUT one (resurrected by a stray
        # add_ref on a deleted object) must not leak forever — found by
        # the refcount fuzz (tests/test_protocol_sim.py).
        self.has_producer = False


class ActorState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.actor_id = spec["actor_id"]
        self.state = A_PENDING
        self.worker_id: Optional[str] = None
        self.addr: Optional[str] = None
        self.restarts_left = spec.get("max_restarts", 0)
        self.name = spec.get("name")
        self.namespace = spec.get("namespace", "default")
        self.detached = spec.get("detached", False)
        self.death_reason: Optional[str] = None
        self.incarnation = 0


class PgState:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str,
                 name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles              # requested resources per bundle
        self.strategy = strategy
        self.name = name
        self.state = PENDING                # pending|ready|removed
        self.assignment: List[Optional[str]] = [None] * len(bundles)  # node ids
        self.bundle_avail: List[Dict[str, float]] = [dict(b) for b in bundles]


# The GcsServer living in THIS process, if any (head == driver process).
# Worker.rpc short-circuits to it; see the note in GcsServer.__init__.
_INPROC_SERVER: Optional["GcsServer"] = None

# RPC kinds a FENCED head (a higher ledger epoch was claimed by a
# promoted standby — DESIGN.md §4l) still answers: pure reads that help
# an operator inspect the fenced process.  Everything else drops the
# connection so the caller's reconnect path re-dials the promoted head.
_FENCED_OK_KINDS = frozenset({
    "ping", "debug_dump", "timeline", "kv_get", "kv_mget", "kv_keys",
    "peek_meta", "pg_table", "list_nodes", "list_actors", "list_tasks",
    "list_objects", "list_workers", "cluster_resources", "store_stats",
    "metrics_query", "fleet_state", "fleet_events", "raylet_table",
    "resource_demand", "autopilot_status", "profile_query",
    "debug_incidents"})


class GcsServer:
    def __init__(self, session: Session, head_resources: Dict[str, float]):
        # Sanitizer first (RAY_TPU_RESOURCE_SANITIZER=1, §4f): every
        # acquisition below — shm maps, the listener, worker dials —
        # must be discharged by shutdown(), so tracking starts here
        from ray_tpu._private import resource_sanitizer
        resource_sanitizer.maybe_install()
        self.session = session
        # Flight recorder (DESIGN.md §4h): crash-surviving mmap ring in
        # the session dir recording recent frames / dispatch decisions;
        # installed before any serve thread so nothing escapes it.
        from ray_tpu._private import flight_recorder
        flight_recorder.maybe_install(session.path, "gcs")
        # Sampling profiler (DESIGN.md §4o): the head samples itself
        # too; its deltas skip the KV hop — the monitor loop drains
        # them straight into the ProfileStore below.
        from ray_tpu.util import profiler as profiler_mod
        profiler_mod.maybe_install("gcs")
        self.store = ShmObjectStore(spill_dir=str(session.spill_dir))
        # Native C++ slab store: the small-object data plane (workers attach
        # and read/write directly; the GCS owns lifecycle + refcount deletes).
        self.slab = None
        if GLOBAL_CONFIG.use_native_store:
            from ray_tpu.native import SlabStore
            self.slab = SlabStore.create(
                session.slab_path(),
                GLOBAL_CONFIG.slab_memory_mb * 1024 * 1024)
        # --- lock domains (DESIGN.md §4c; DAG in lock_watchdog.py) ---
        # All six domain locks are created together, BEFORE any server
        # thread starts, so RAY_TPU_LOCK_WATCHDOG=1 can wrap the complete
        # set and assert the acquisition DAG at runtime (§4d).
        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)
        # Object waiters under their own lock: seals (global lock held)
        # take it briefly to wake the exact blocked get/wait RPCs;
        # waiter registration/unregistration never touches the global
        # lock.  Lock order: self.lock -> _waiter_lock, never reversed.
        self._waiter_lock = threading.Lock()
        # KV plane (incl. the metrics receipt index) off the global lock:
        # per-process metrics publishers and config readers must not
        # contend with the scheduler.  Lock order: self.lock -> _kv_lock.
        self._kv_lock = threading.Lock()
        self._events_lock = threading.Lock()  # timeline event buffer
        self._dedup_lock = threading.Lock()   # reply-replay cache
        # remote-spool delete queue (leaf under the global lock: _decref
        # enqueues while holding it)
        self._peer_delete_lock = threading.Lock()
        # snapshot writer ordering lock — ABOVE the global lock in the
        # DAG (capture under lock, write file under persist only)
        self._persist_lock = threading.Lock()
        from ray_tpu._private.lock_watchdog import watchdog_enabled, \
            wrap_gcs_locks
        if watchdog_enabled():
            wrap_gcs_locks(self)

        # Fast-path tables (GCS locking discipline, DESIGN.md §4c):
        # ``_sealed`` maps oid -> a reply-ready meta dict for objects in a
        # terminal state.  Written ONLY under self.lock (at seal / delete /
        # loss transitions), read LOCK-FREE (CPython dict reads are atomic
        # under the GIL) by get_meta/peek_meta/wait — the sealed-object
        # read path never touches the global lock.  Remote-spooled objects
        # appear only as markers (terminal-state visibility for the
        # waiter handshake and peek/wait); their replies need a live
        # node-table address lookup, so reads fall to the slow path.
        self._sealed: Dict[str, dict] = {}   # guarded by: lock (writes)

        self.nodes: Dict[str, NodeState] = {}          # guarded by: lock
        self.workers: Dict[str, WorkerState] = {}      # guarded by: lock
        self.objects: Dict[str, ObjMeta] = {}          # guarded by: lock
        # guarded by: lock
        self.client_refs: Dict[str, Dict[str, int]] = defaultdict(dict)
        self.pending_tasks: deque = deque()            # guarded by: lock
        # backlog composition by resource class (see _push_pending)
        # guarded by: lock
        self._pending_counts: Dict[str, int] = {
            "cpu": 0, "tpu": 0, "zero": 0, "special": 0}
        self.dep_waiting: Dict[str, List[dict]] = {}   # guarded by: lock
        # oid → waiter records for blocked get/wait RPCs: seals wake the
        # exact waiters instead of notify_all-storming every blocked call
        # into an O(oids) rescan (that was quadratic in batch gets)
        # guarded by: _waiter_lock
        self._object_waiters: Dict[str, List[dict]] = {}
        # `ray_tpu stack` calls                          guarded by: lock
        self._stack_reqs: List[Dict[str, str]] = []
        self.infeasible_tasks: List[dict] = []         # guarded by: lock
        # task_id -> (worker, spec)                      guarded by: lock
        self.running: Dict[str, Tuple[str, dict]] = {}
        self.actors: Dict[str, ActorState] = {}        # guarded by: lock
        self.named_actors: Dict[Tuple[str, str], str] = {}  # guarded by: lock
        self.functions: Dict[str, bytes] = {}          # guarded by: lock
        # guarded by: _kv_lock
        self.kv: Dict[str, Dict[bytes, bytes]] = defaultdict(dict)
        self.pgs: Dict[str, PgState] = {}              # guarded by: lock
        self.lineage: Dict[str, dict] = {}             # guarded by: lock
        self.lineage_order: deque = deque(maxlen=20000)  # guarded by: lock
        # timeline events                        guarded by: _events_lock
        self.events: List[dict] = []
        # fleet lifecycle feed (DESIGN.md §4j): bounded ring of node
        # add/drain/remove + elastic re-mesh events, consumed by the
        # elasticity manager and `ray_tpu status` through the
        # ``fleet_events`` cursor RPC  guarded by: _events_lock
        self._fleet_events: deque = deque(maxlen=512)
        self._fleet_event_seq = 0             # guarded by: _events_lock
        self._last_remesh: Optional[dict] = None  # guarded by: _events_lock
        self.dead_clients: Set[str] = set()            # guarded by: lock
        # in-flight chunked uploads                      guarded by: lock
        self._staging: Dict[str, dict] = {}
        # relay dedup                                    guarded by: lock
        self._remote_pulls: Dict[str, threading.Event] = {}
        # rc-0-at-seal grace                             guarded by: lock
        self._graceful_free: Dict[str, float] = {}
        self._last_metrics_sweep = 0.0        # dead-snapshot KV hygiene
        # head-side receipt time per __metrics__/ key: the sweep's grace
        # window must not trust publisher-host wall clocks (cross-host
        # skew > grace would reap a dying worker's final flush instantly)
        # guarded by: _kv_lock
        self._metrics_key_seen: Dict[str, float] = {}
        # __profile__/ receipts, same head-side receipt-time hygiene
        # guarded by: _kv_lock
        self._profile_key_seen: Dict[str, float] = {}
        # Metrics time-series store (DESIGN.md §4k): every __metrics__/
        # snapshot the KV plane already receives is ALSO ingested into
        # head-resident fixed-memory rings (zero new RPCs), queryable
        # via the metrics_query op and feeding the always-on straggler /
        # SLO-burn detectors (ticked by the monitor loop, anomalies into
        # the fleet-event feed).  The TSDB has its own leaf lock
        # (TSDB_LOCK_DAG) and is never called with a GCS lock held.
        self._tsdb = None
        self._detectors: List = []
        self._last_detector_check = 0.0
        # Ledger replication (DESIGN.md §4l): WAL + warm-standby hub,
        # created below once the durable tables are restored.  The
        # attribute exists from here so every _repl_record call site is
        # safe during __init__.  ``_fenced`` is flipped (only ever
        # False->True, by the hub's drain thread) when a HIGHER ledger
        # epoch appears in the session dir — a promoted standby owns
        # the ledger now; this head must drop mutating conns so their
        # clients re-dial the new endpoint.
        self._repl_hub = None
        self._fenced = False
        self.ledger_epoch = 0
        if GLOBAL_CONFIG.metrics_enabled and GLOBAL_CONFIG.tsdb_enabled:
            from ray_tpu.util.metrics_catalog import SLO_RULES
            from ray_tpu.util.tsdb import (SloBurnAlerter,
                                           StragglerDetector, TSDB)
            self._tsdb = TSDB(
                max_series=GLOBAL_CONFIG.tsdb_max_series,
                raw_slots=GLOBAL_CONFIG.tsdb_raw_samples)
            self._detectors = [
                StragglerDetector(
                    self._tsdb,
                    window_s=GLOBAL_CONFIG.tsdb_straggler_window_s,
                    ratio=GLOBAL_CONFIG.tsdb_straggler_ratio),
                SloBurnAlerter(self._tsdb, SLO_RULES)]
        # Profiling plane (DESIGN.md §4o): every __profile__/ receipt
        # the KV plane already gets is handed to the head-resident
        # windowed ProfileStore (fixed memory; history survives the
        # publisher's death).  Answered by the profile_query op; the
        # store has its own leaf lock (PROFILER_LOCK_DAG) and is never
        # called with a GCS lock held.
        self._profile_store = None
        self._last_profile_flush = 0.0        # monitor thread only
        if GLOBAL_CONFIG.profiler_enabled:
            from ray_tpu.util.profiler import ProfileStore
            self._profile_store = ProfileStore()
        # Incident capture (§4o): node_id -> (capture time, bundle id).
        # Both writers (the detector pass and the autopilot's actuator
        # callback) run on the monitor thread, so this dedup ledger is
        # single-threaded — monitor thread only, no lock.
        self._incident_recent: Dict[str, Tuple[float, str]] = {}
        # Fleet autopilot (DESIGN.md §4n): the reflex arc turning the
        # detectors' fleet events + TSDB history into bounded
        # remediation actions.  Ticked from the monitor loop; reads the
        # fleet-event ring through its own cursor; actuates through the
        # internal drain/undrain paths and whatever autoscaler attaches
        # itself via AutoscalerLoop.  Off by default (autopilot_enabled).
        self._autopilot = None
        self._autopilot_cursor = 0
        self._last_autopilot = 0.0
        if GLOBAL_CONFIG.autopilot_enabled:
            from ray_tpu.elastic.autopilot import (Autopilot,
                                                   AutopilotConfig,
                                                   GcsActuator)
            self._autopilot = Autopilot(
                AutopilotConfig.from_global_config(), GcsActuator(self))
        # reply cache for client-supplied request ids: makes the worker's
        # one post-reconnect retry exactly-once against a still-live GCS
        # (non-idempotent mutations must not double-apply when only the
        # channel broke, not the server)
        # guarded by: _dedup_lock
        self._dedup_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        # guarded by: _dedup_lock
        self._dedup_pending: Dict[tuple, threading.Event] = {}
        # Ledgers already torn down by release_all (lock held): a pin for
        # a closed call ledger arriving LATE (cross-channel race — the
        # caller's add_refs coalescing in flight while the actor's
        # release_all lands) must be dropped, not applied; an orphaned
        # ledger entry would pin its objects forever.
        # guarded by: lock
        self._closed_ledgers: "OrderedDict[str, None]" = OrderedDict()
        # remote-spool deletions, batched per holder node (see _decref);
        # the drain thread starts below, after _shutdown exists
        # guarded by: _peer_delete_lock
        self._peer_delete_q: Dict[str, List[str]] = defaultdict(list)
        self._peer_delete_event = threading.Event()
        # pooled data-plane conns to holder nodes (relay pull-throughs +
        # spool deletes reuse one dial+HMAC per holder); internal lock,
        # never held together with any GCS lock
        from ray_tpu._private.data_plane import DataPlanePool
        self._data_pool = DataPlanePool()
        self.driver_ids: Set[str] = set()              # guarded by: lock
        self.log_sink = None                              # callable(line)
        self._shutdown = False
        self._spawn_counter = 0
        threading.Thread(target=self._peer_delete_loop, daemon=True,
                         name="gcs-peer-delete").start()

        # server incarnation id: clients detect a true head RESTART (vs a
        # transient channel break) by comparing this across reconnects, and
        # resubmit their in-flight owned tasks (owner-based lineage — the
        # reference keeps task lineage in the owning worker's TaskManager)
        import uuid as _uuid
        self.epoch = _uuid.uuid4().hex

        self.head_node_id = NodeID.new()
        self.add_node_internal(self.head_node_id, head_resources, is_head=True)
        # Warm worker pool (reference: RAY_prestart_worker_first_driver /
        # worker-pool prestart): fork N plain workers NOW so the first
        # tasks — and Serve replica scale-ups (SURVEY.md §7.3 TPU cold
        # starts) — skip the worker-process boot (~10s on 1-core hosts,
        # measured in serve_bench_r04.json).  Under the lock: the peer-
        # delete and persist threads are already running, and
        # _spawn_worker mutates the worker table (rtlint unguarded).
        with self.lock:
            for _ in range(int(GLOBAL_CONFIG.prestart_workers or 0)):
                self._spawn_worker(self.head_node_id)

        # GCS fault tolerance (reference: GCS restart w/ Redis persistence,
        # SURVEY.md §5.3): durable tables snapshot to <session>/gcs_state;
        # a head started over a session dir that has one restores them and
        # gives surviving worker processes a grace window to reattach.
        self._snapshot_path = session.path / "gcs_state" / "snapshot.pkl"
        # (_persist_lock is created with the other lock domains above so
        # the watchdog wrap covers it)
        self._persist_event = threading.Event()
        self._prev_snapshot_wal_seq = 0  # guarded by: _persist_lock
        self._restored_at: Optional[float] = None
        if GLOBAL_CONFIG.gcs_snapshot:
            try:
                if self._restore_durable():
                    self._restored_at = time.monotonic()
            except Exception:  # noqa: BLE001 - corrupt snapshot: fresh start
                logger.exception("failed to restore GCS snapshot; "
                                 "starting fresh")
        if GLOBAL_CONFIG.gcs_snapshot:
            # Claim the next ledger epoch (fsynced): any still-alive
            # older head observes the bump at its fence poll and stops
            # mutating — the split-brain guard (DESIGN.md §4l).
            from ray_tpu._private import replication
            self.ledger_epoch = replication.claim_epoch(session.path)
            if GLOBAL_CONFIG.gcs_wal:
                # WAL + warm-standby replication hub: handler threads
                # record durable mutations (O(1) buffer append); the
                # hub's drain thread owns fsync, streaming, rotation,
                # and the epoch-fence poll.
                tsdb_cb = None
                if self._tsdb is not None:
                    tsdb_cb = self._tsdb.export_since
                self._repl_hub = replication.ReplicationHub(
                    session.path, self.ledger_epoch,
                    snapshot_cb=self._capture_durable_state,
                    tsdb_export_cb=tsdb_cb,
                    on_fenced=self._on_fenced,
                    fsync=GLOBAL_CONFIG.gcs_wal_fsync)
            threading.Thread(target=self._persist_loop, name="gcs-persist",
                             daemon=True).start()

        self.rpc_path = session.socket_path("gcs.sock")
        self._listener = protocol.make_listener(self.rpc_path)
        self._threads: List[threading.Thread] = []
        try:
            t = threading.Thread(target=self._accept_loop,
                                 name="gcs-accept", daemon=True)
            t.start()
            self._threads.append(t)
            m = threading.Thread(target=self._monitor_loop,
                                 name="gcs-monitor", daemon=True)
            m.start()
            self._threads.append(m)
        except BaseException:
            # a failed boot returns no server object: the bound socket
            # file must not survive it (the next head would unlink a
            # listener it does not own)
            self._listener.close()
            raise
        # In-process dispatch short-circuit (reference analog: core_worker
        # short-circuiting its local raylet/plasma): a driver whose head
        # lives in ITS OWN process skips the socket + serve-thread wakeup
        # per RPC — Worker.rpc consults this global, guarded by rpc_path.
        global _INPROC_SERVER
        _INPROC_SERVER = self

    # ----------------------------------------------------- fault tolerance
    def _persist_durable(self) -> None:
        """Mark the durable tables dirty; a dedicated writer thread
        snapshots them shortly after (debounced).  Mutating handlers call
        this — cheap enough for any path, including ones holding the cv
        lock — and the crash window is bounded by the debounce interval."""
        if not GLOBAL_CONFIG.gcs_snapshot:
            return
        self._persist_event.set()

    def _persist_loop(self) -> None:
        while not self._shutdown:
            if not self._persist_event.wait(timeout=0.5):
                continue
            time.sleep(0.05)  # coalesce bursts of mutations
            self._persist_event.clear()
            if self._fenced:
                # a promoted standby owns the ledger: this head must
                # never clobber the new head's snapshot generations
                continue
            try:
                self._write_snapshot()
            except Exception:  # noqa: BLE001 - keep serving; retry next tick
                logger.exception("GCS snapshot write failed")
                self._persist_event.set()

    def _on_fenced(self, seen_epoch: int) -> None:
        """Hub drain thread: a higher ledger epoch appeared in the
        session dir — refuse mutations from here on (see _serve_conn /
        local_call; mutating conns are dropped so clients re-dial the
        promoted head's re-bound socket)."""
        self._fenced = True

    def _repl_record(self, *op) -> None:
        """Record one durable ledger mutation into the replication WAL
        (no-op without the hub; O(1) buffer append — legal under any
        GCS lock, see REPL_LOCK_DAG)."""
        hub = self._repl_hub
        if hub is not None:
            hub.record(*op)

    def _repl_actor_locked(self, a: "ActorState") -> None:
        """Lock held.  Record an actor's durable projection after any
        FSM transition — the same shape the snapshot captures (DEAD
        actors are absent from snapshots, so DEAD records a delete,
        which also keeps the standby's tables == the capture)."""
        if self._repl_hub is None:
            return
        if a.state == A_DEAD:
            self._repl_hub.record("actor", a.actor_id, None)
        else:
            self._repl_hub.record(
                "actor", a.actor_id,
                {"spec": {k: v for k, v in a.spec.items()
                          if not k.startswith("_")},
                 "state": a.state, "restarts_left": a.restarts_left,
                 "incarnation": a.incarnation})

    def _capture_durable_state(self) -> dict:
        """Capture the durable tables under lock + _kv_lock (reference:
        the GCS tables Redis persists — actors, PGs, KV, function
        exports).  The WAL position is read INSIDE the critical section:
        every record with seq <= wal_seq is reflected in the captured
        tables, and replaying any later (or overlapping) record on top
        is idempotent — the snapshot+WAL equivalence contract the
        standby and restart paths both lean on."""
        with self.lock, self._kv_lock:
            state = {
                # __metrics__/ snapshots are ephemeral telemetry: a
                # restored head must not resurrect dead workers'
                # series, and busy-cluster snapshots must not grow by
                # one metrics payload per worker
                # empty namespaces pruned: apply_op prunes a namespace
                # when its last key is deleted (and a metrics-only one
                # would capture as {}), so the capture must too or the
                # snapshot+WAL == capture equivalence oracle diverges
                "kv": {ns: flt for ns, t in self.kv.items()
                       if (flt := {k: v for k, v in t.items()
                                   if not is_metrics_key(k)
                                   and not is_profile_key(k)})},
                "functions": dict(self.functions),
                "named_actors": dict(self.named_actors),
                "actors": {
                    aid: {"spec": {k: v for k, v in a.spec.items()
                                   if not k.startswith("_")},
                          "state": a.state,
                          "restarts_left": a.restarts_left,
                          "incarnation": a.incarnation}
                    for aid, a in self.actors.items()
                    if a.state != A_DEAD},
                "pgs": {pid: {"bundles": p.bundles,
                              "strategy": p.strategy, "name": p.name}
                        for pid, p in self.pgs.items()
                        if p.state != "removed"},
                "shm_objects": {
                    oid: m.size for oid, m in self.objects.items()
                    if m.loc == "shm" and m.state == READY},
                "driver_ids": set(self.driver_ids),
                "ledger_epoch": self.ledger_epoch,
                "wal_seq": (self._repl_hub.seq()
                            if self._repl_hub is not None else 0),
            }
        return state

    def _write_snapshot(self) -> None:
        """Capture + write under one ordering lock so a slow writer can
        never clobber a newer snapshot with stale state.  The write is
        crash-safe (fsync tmp + dir, previous generation kept — see
        replication.write_snapshot_file) and rotates the WAL: records
        covered by this snapshot are no longer needed for replay."""
        from ray_tpu._private import replication
        with self._persist_lock:
            state = self._capture_durable_state()
            replication.write_snapshot_file(self._snapshot_path, state)
            # Rotate the WAL one GENERATION behind: segments are only
            # deleted once covered by the PREVIOUS snapshot too, so the
            # .prev fallback (torn-newest restore) always finds the WAL
            # tail that bridges it forward.
            covered, self._prev_snapshot_wal_seq = \
                self._prev_snapshot_wal_seq, state["wal_seq"]
        if self._repl_hub is not None:
            self._repl_hub.rotate(covered)

    def _restore_durable(self) -> bool:
        """Rebuild durable tables from the newest consistent durable
        state: the newest readable snapshot generation (a torn newest
        falls back to the previous one) plus the fsynced WAL tail
        replayed on top (replication.load_durable_state).  Returns True
        when anything was restored.  Actors come back RESTARTING: their
        processes may still be alive (workers outlive the head and
        reconnect — see worker.run_worker_loop); if one doesn't
        reattach within gcs_restore_grace_s the normal restart path
        (max_restarts) takes over.

        Everything is parsed into temporaries FIRST, then applied — a
        malformed/old-format snapshot must fail before mutating any
        table, or restored actors would sit RESTARTING forever with no
        grace timer running."""
        from ray_tpu._private import replication
        state = replication.load_durable_state(
            self.session.path, snapshot_path=self._snapshot_path)
        if state is None:
            return False
        restored_actors = []
        for aid, rec in state["actors"].items():
            a = ActorState(rec["spec"])
            a.state = A_RESTARTING
            a.restarts_left = rec["restarts_left"]
            a.incarnation = rec["incarnation"]
            restored_actors.append((aid, a))
        restored_pgs = [
            (pid, PgState(pid, rec["bundles"], rec["strategy"],
                          rec["name"]))
            for pid, rec in state["pgs"].items()]
        # strip metrics keys defensively: current snapshots never contain
        # them, but a pre-exemption snapshot must not resurrect dead
        # publishers' series (and such keys would be invisible to the
        # sweep's receipt index)
        kv_tables = {ns: {k: v for k, v in t.items()
                          if not is_metrics_key(k)
                          and not is_profile_key(k)}
                     for ns, t in state["kv"].items()}
        functions = dict(state["functions"])
        named = dict(state["named_actors"])
        # only segments this snapshot knows about — a host-global scan
        # would adopt (and later evict/delete) segments belonging to
        # OTHER live sessions on the same /dev/shm
        from ray_tpu._private.shm_store import _seg_path
        shm_objects = []
        for oid, size in state.get("shm_objects", {}).items():
            try:
                if _seg_path(oid).stat().st_size >= 1:
                    shm_objects.append((oid, size))
            except OSError:
                continue

        logger.info("restoring GCS state from %s (%d actors, %d pgs, "
                    "%d shm objects)", self._snapshot_path,
                    len(restored_actors), len(restored_pgs),
                    len(shm_objects))
        with self.cv:
            with self._kv_lock:
                for ns, table in kv_tables.items():
                    self.kv[ns].update(table)
            self.functions.update(functions)
            self.named_actors.update(named)
            for aid, a in restored_actors:
                self.actors[aid] = a
            from ray_tpu._private.pg_scheduler import schedule_bundles
            for pid, pg in restored_pgs:
                # old node ids are gone; re-place on the current nodes
                # (more re-placements happen lazily in _h_pg_wait as
                # nodes rejoin)
                assignment = schedule_bundles(
                    [n for n in self.nodes.values() if n.schedulable()],
                    pg.bundles, pg.strategy)
                if assignment is not None:
                    for i, node_id in enumerate(assignment):
                        self.nodes[node_id].acquire(pg.bundles[i])
                        pg.assignment[i] = node_id
                    pg.state = READY
                self.pgs[pid] = pg
            for oid, size in shm_objects:
                self.store.adopt(oid, size)
                meta = self.objects.get(oid)
                if meta is None:
                    meta = self.objects[oid] = ObjMeta()
                meta.state = READY
                meta.loc = "shm"
                meta.size = size
                self._publish_sealed_locked(oid, READY, "shm", None, size)
        return True

    def _restore_grace_check(self) -> None:
        """After the reattach grace window, push restored actors whose
        worker never came back through the normal death/restart path."""
        if self._restored_at is None:
            return
        if time.monotonic() - self._restored_at \
                < GLOBAL_CONFIG.gcs_restore_grace_s:
            return
        self._restored_at = None
        stranded = []
        with self.cv:
            for a in self.actors.values():
                if a.state == A_RESTARTING and a.worker_id is None and \
                        not any(w.actor_id == a.actor_id
                                for w in self.workers.values()):
                    stranded.append(a.actor_id)
        for aid in stranded:
            with self.cv:
                a = self.actors.get(aid)
                if a is None or a.state != A_RESTARTING \
                        or a.worker_id is not None:
                    continue
                logger.info("restored actor %s did not reattach; routing "
                            "through the restart path", aid)
                # the normal death path enforces max_restarts (budget
                # decrement, A_DEAD + named-table cleanup when exhausted)
                self._actor_worker_died(aid)
        if stranded:
            self._pump()

    # ------------------------------------------------------------------ nodes
    def add_node_internal(self, node_id: str, resources: Dict[str, float],
                          is_head: bool = False,
                          labels: Optional[Dict[str, str]] = None,
                          remote: bool = False,
                          data_addr: Optional[str] = None,
                          data_proto: int = 0) -> str:
        if data_addr and data_proto:
            # pre-seed the agent's advertised data-plane version so the
            # head's pooled conns skip the per-conn hello round trip
            self._data_pool.set_proto(data_addr, data_proto)
        with self.cv:
            res = dict(resources)
            res.setdefault("CPU", float(os.cpu_count() or 4) if is_head else 1.0)
            node = NodeState(node_id, res, labels)
            node.is_remote = remote
            node.data_addr = data_addr
            node.data_proto = int(data_proto or 0)
            # node-id resource enables NodeAffinity via plain resource matching
            node.resources_total[f"node:{node_id}"] = 1.0
            node.resources_avail[f"node:{node_id}"] = 1.0
            self.nodes[node_id] = node
            self.cv.notify_all()
        self._fleet_event("node_added", node_id,
                          labels=dict(labels or {}))
        return node_id

    def remove_node_internal(self, node_id: str) -> None:
        """Cluster fixture: simulate node failure (SURVEY.md §4 Cluster.remove_node)."""
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None:
                return
            node.alive = False
            was_draining = node.phase == "draining"
            node.phase = "terminating"
            # raylet node: reclaim the outstanding lease ledger FIRST so
            # granted work re-queues before the workers are declared dead
            self._reclaim_raylet_leases_locked(node)
            with node.raylet_conn_lock:
                node.raylet_conn = None
            workers = [self.workers[w] for w in list(node.workers)]
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
        with self.cv:
            for w in workers:
                self._handle_worker_death(w)
            # objects whose primary copy lived there are lost → reconstruction
            for oid, meta in self.objects.items():
                if meta.node_id == node_id and meta.state == READY and meta.loc != "inline":
                    self._mark_object_lost(oid, meta)
            del self.nodes[node_id]
            self.cv.notify_all()
        self._fleet_event("node_removed", node_id,
                          was_draining=was_draining)
        self._pump()

    # ---------------------------------------------------------------- objects
    def _get_or_create_meta(self, oid: str) -> ObjMeta:
        meta = self.objects.get(oid)
        if meta is None:
            meta = ObjMeta()
            self.objects[oid] = meta
        return meta

    def _publish_sealed_locked(self, oid: str, state: str, loc: str,
                               data: Optional[bytes], size: int) -> None:
        """Lock held.  Publish a terminal meta to the lock-free read
        table — the ONE place the reply-entry shape is built, so the
        fast path can never drift from the slow-path reply.  Remote-
        spooled objects get a MARKER entry: it makes the seal visible to
        the register-then-recheck waiter handshake and to peek/wait
        (terminal-state checks), but _read_sealed_fast refuses to serve
        it (the reply needs a live node-table address lookup, so those
        reads stay on the slow path)."""
        self._sealed[oid] = {"state": state, "loc": loc, "data": data,
                             "size": size}

    def _seal_object(self, oid: str, loc: str, data: Optional[bytes], size: int,
                     node_id: Optional[str], contained: List[str],
                     lineage_task: Optional[str] = None) -> None:
        meta = self._get_or_create_meta(oid)
        meta.state = READY
        meta.has_producer = False
        meta.loc = loc
        meta.data = data
        meta.size = size
        meta.node_id = node_id
        meta.contained = contained
        # publish to the lock-free read table BEFORE waking waiters: a
        # reader that observes the wake must find the entry
        self._publish_sealed_locked(oid, READY, loc, data, size)
        self._promote_dep_waiters(oid)
        self._notify_object_waiters(oid)
        if lineage_task:
            meta.lineage_task = lineage_task
        for c in contained:
            cm = self._get_or_create_meta(c)
            cm.refcount += 1  # the container holds a ref on nested objects
        if loc == "shm":
            # segment survives a head crash; keep the snapshot's shm index
            # current so a restarted head re-adopts it (just sets an event)
            self._repl_record("shm", oid, size)
            self._persist_durable()
        if meta.refcount <= 0:
            # Sealed with zero refs — e.g. an actor result whose caller
            # died mid-call: nothing will ever release it.  Free after a
            # grace period, NOT now: (a) the caller's add_refs oneway may
            # still be in flight on another channel (no cross-channel
            # ordering) and will rescue it, and (b) a just-woken getter
            # needs a moment to read/mmap (unlink under a live mmap is
            # safe by store design, so late frees cannot corrupt reads).
            self._graceful_free[oid] = time.monotonic()

    def _seal_error(self, oid: str, err_bytes: bytes) -> None:
        meta = self._get_or_create_meta(oid)
        meta.state = ERROR
        meta.has_producer = False
        meta.loc = "inline"
        meta.data = err_bytes
        self._publish_sealed_locked(oid, ERROR, "inline", err_bytes, 0)
        self._promote_dep_waiters(oid, errored=True)
        self._notify_object_waiters(oid)

    def _mark_object_lost(self, oid: str, meta: ObjMeta) -> None:
        self._sealed.pop(oid, None)  # no longer readable without the lock
        if meta.loc == "shm":
            # no longer a restorable segment: drop it from the durable
            # shm index so a promoted/restarted head won't re-adopt it
            self._repl_record("shm", oid, None)
        if meta.lineage_task and meta.lineage_task in self.lineage:
            meta.state = PENDING
            meta.has_producer = True  # the reconstruction below is the
            # producer; without this a zero-ref decref would zombie-delete
            # the meta out from under it
            meta.data = None
            spec = dict(self.lineage[meta.lineage_task])
            spec["is_reconstruction"] = True
            logger.info("reconstructing %s via task %s", oid, spec["task_id"])
            self._push_pending(spec)
        else:
            owner_dead = oid[:16] in self.dead_clients
            e = exc.OwnerDiedError(oid) if owner_dead else exc.ObjectLostError(oid)
            from ray_tpu._private.serialization import serialize_to_bytes
            meta.state = ERROR
            meta.loc = "inline"
            meta.data = serialize_to_bytes(e)[0]
            self._publish_sealed_locked(oid, ERROR, "inline", meta.data, 0)
            # terminal transition outside _seal_error: wake dep-parked
            # specs and object waiters here too
            self._promote_dep_waiters(oid, errored=True)
            self._notify_object_waiters(oid)

    def _decref(self, oid: str, n: int = 1) -> None:
        meta = self.objects.get(oid)
        if meta is None:
            return
        meta.refcount -= n
        if meta.refcount <= 0 and meta.state == PENDING \
                and not meta.has_producer:
            # zombie: zero refs, nothing will ever seal it — drop the
            # entry (no data to free; a late seal re-creates it cleanly)
            del self.objects[oid]
            return
        if meta.refcount <= 0 and meta.state != PENDING:
            self._sealed.pop(oid, None)  # unpublish BEFORE freeing data
            for c in meta.contained:
                self._decref(c)
            if meta.loc in ("shm", "spilled"):
                self.store.delete_object(oid)
                self._repl_record("shm", oid, None)
            elif meta.loc == "slab" and self.slab is not None:
                self.slab.delete(oid)
            elif meta.loc == "remote":
                node = self.nodes.get(meta.node_id)
                if node is not None and node.data_addr:
                    # batched per holder on one background worker: a bulk
                    # release of N remote objects must not fork N threads
                    # each paying a TCP connect (mirrors the debounced
                    # snapshot writer's shape)
                    with self._peer_delete_lock:
                        self._peer_delete_q[node.data_addr].append(oid)
                    self._peer_delete_event.set()
            del self.objects[oid]

    def _peer_delete_loop(self) -> None:
        """Drain queued remote-spool deletions, one connection per holder
        per drain (reference: ObjectManager frees remote copies without a
        per-object connection storm).  Holders drain concurrently so one
        dead/unreachable host's 3s connect timeout can't head-of-line
        block frees on healthy nodes; batches for addresses no live node
        advertises are dropped (the agent's shutdown rmtree already freed
        that spool)."""
        while not self._shutdown:
            self._peer_delete_event.wait(1.0)
            if self._shutdown:
                return
            try:
                self._peer_delete_event.clear()
                with self._peer_delete_lock:
                    if not self._peer_delete_q:
                        continue
                    batches = dict(self._peer_delete_q)
                    self._peer_delete_q.clear()
                with self.lock:
                    live = {n.data_addr for n in self.nodes.values()
                            if n.alive and n.data_addr}
                threads = [threading.Thread(
                    target=self._data_pool.delete_batch,
                    args=(addr, oids), daemon=True,
                    name="gcs-peer-delete-batch")
                           for addr, oids in batches.items() if addr in live]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(10.0)
            except Exception:  # noqa: BLE001 - the only drain thread:
                # an unexpected error (e.g. thread exhaustion) must not
                # kill it, or remote spools leak forever
                logger.exception("peer-delete drain pass failed")

    # ------------------------------------------------------------- scheduling
    def _task_resources(self, spec: dict) -> Dict[str, float]:
        req = dict(spec.get("resources") or {})
        req["CPU"] = float(spec.get("num_cpus", 1))
        if spec.get("num_tpus"):
            req["TPU"] = float(spec["num_tpus"])
        return {k: v for k, v in req.items() if v > 0}

    def _deps_status(self, spec: dict) -> str:
        """ready | waiting | error:<oid>"""
        for dep in spec.get("deps", ()):
            meta = self.objects.get(dep)
            if meta is None or meta.state == PENDING:
                return "waiting"
            if meta.state == ERROR:
                return f"error:{dep}"
        return "ready"

    def _pick_node(self, spec: dict, req: Dict[str, float]) -> Optional[NodeState]:
        strategy = spec.get("scheduling_strategy") or "DEFAULT"
        alive = [n for n in self.nodes.values() if n.schedulable()]
        if isinstance(strategy, dict) and strategy.get("type") == "node_affinity":
            node = self.nodes.get(strategy["node_id"])
            if node is not None and node.schedulable() and node.fits(req):
                return node
            if strategy.get("soft"):
                strategy = "DEFAULT"
            else:
                return None
        if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
            return None  # handled by _pick_pg_node
        fitting = [n for n in alive if n.fits(req)]
        if not fitting:
            return None
        if strategy == "SPREAD":
            fitting.sort(key=lambda n: n.load())
            return fitting[0]
        # hybrid (reference hybrid_policy): pack onto low-index nodes until the
        # spread threshold, then least-loaded.
        thresh = GLOBAL_CONFIG.scheduler_spread_threshold
        for n in fitting:
            if n.load() < thresh:
                return n
        fitting.sort(key=lambda n: n.load())
        return fitting[0]

    def _pick_pg_node(self, spec: dict, req: Dict[str, float]):
        st = spec["scheduling_strategy"]
        pg = self.pgs.get(st["pg_id"])
        if pg is None or pg.state != READY:
            return None, None
        idxs = [st["bundle_index"]] if st.get("bundle_index", -1) >= 0 \
            else range(len(pg.bundles))
        for i in idxs:
            avail = pg.bundle_avail[i]
            if all(avail.get(k, 0.0) >= v - 1e-9 for k, v in req.items()):
                node = self.nodes.get(pg.assignment[i])
                if node is not None and node.alive:
                    return node, (pg, i)
        return None, None

    def _piggyback_worker(self, node: NodeState, req: Dict[str, float],
                          need_tpu: bool) -> Optional[WorkerState]:
        """A busy worker on ``node`` whose running lease matches ``req``
        and whose pipeline has room (lock held)."""
        depth = GLOBAL_CONFIG.worker_pipeline_depth
        if depth <= 0:
            return None
        for wid in node.workers:
            w = self.workers.get(wid)
            if (w is None or w.state != "busy" or w.blocked
                    or w.actor_id is not None
                    or w.tpu_capable != need_tpu
                    or len(w.pipeline) >= depth):
                continue
            cur = w.current_task
            if (cur is None or cur.get("is_actor_creation")
                    or cur.get("_pg_claim") is not None):
                continue
            if cur.get("_req") != req:
                continue
            return w
        return None

    def _idle_worker_on(self, node: NodeState,
                        need_tpu: bool = False) -> Optional[WorkerState]:
        """Pop an idle worker matching the device requirement.  TPU work
        only runs on TPU-capable workers (spawned with device access);
        CPU work prefers plain workers but may ride a TPU-capable one."""
        skipped = []
        found = None
        fallback = None  # tpu-capable worker a CPU task may ride if no
        # plain worker is idle (but plain ones are preferred)
        while node.idle_workers:
            wid = node.idle_workers.popleft()
            w = self.workers.get(wid)
            if w is None or w.state != "idle":
                continue
            if need_tpu and not w.tpu_capable:
                skipped.append(wid)
                continue
            if not need_tpu and w.tpu_capable:
                if fallback is None:
                    fallback = w
                else:
                    skipped.append(wid)
                continue
            found = w
            break
        if found is None:
            found = fallback
        elif fallback is not None:
            skipped.append(fallback.worker_id)
        node.idle_workers.extendleft(reversed(skipped))
        return found

    def _spawn_worker(self, node_id: str, tpu: bool = False) -> None:
        """Fork a new worker process for a node (reference: WorkerPool pop/fork)."""
        self._spawn_counter += 1
        env = dict(os.environ)
        env.update(GLOBAL_CONFIG.to_env())
        env["RTPU_SESSION_DIR"] = str(self.session.path)
        env["RTPU_NODE_ID"] = node_id
        if tpu:
            # TPU-capable worker: keep device access (jax initializes the
            # real platform inside the worker) — spawned on demand when
            # pending work requests TPU resources.
            env["RTPU_TPU_WORKER"] = "1"
            env.pop("JAX_PLATFORMS", None)
            # persistent compile cache: replica/trainer restarts must
            # not re-pay multi-minute XLA compiles (SURVEY.md §7.3)
            GLOBAL_CONFIG.apply_xla_cache_env(env)
        else:
            # Plain workers never grab the TPU: jax must not lock the chip
            # in every spawned process, and the sitecustomize PJRT
            # registration is a 3.4s import tax — shared scrub drops the
            # whole tunnel env set (ray_tpu._private.axon_env).
            from ray_tpu._private.axon_env import scrub_tpu_tunnel
            scrub_tpu_tunnel(env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env, cwd=os.getcwd(),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        w = WorkerState(WorkerID(f"spawn{self._spawn_counter:06d}"), node_id, proc.pid)
        w.proc = proc
        w.tpu_capable = tpu
        # registered properly once the process connects; keep it for monitor
        self.workers[w.worker_id] = w

    def _count_node_workers(self, node: NodeState, include_starting=True,
                            tpu: Optional[bool] = None) -> int:
        """Workers counted against the spawn cap (optionally filtered by
        device capability — TPU and plain workers have separate caps, or a
        cap full of the wrong kind would starve the other forever).

        Blocked workers (parked in get(), CPU released) don't count — else
        nested task chains deadlock once the cap's worth of workers are all
        blocked waiting on children (reference: raylet spawns replacement
        workers for blocked ones).
        """
        n = 0
        for wid in list(self.workers):
            w = self.workers[wid]
            if tpu is not None and w.tpu_capable != tpu:
                continue
            if w.node_id == node.node_id and not w.blocked and w.state in (
                    ("starting",) if include_starting else ()) + ("idle", "busy"):
                n += 1
        return n

    def _pump(self, force: bool = False) -> None:
        """Try to dispatch pending work. Call with lock NOT held.
        ``force`` bypasses the capacity pre-check (periodic safety pump)."""
        with self.cv:
            self._pump_locked(force=force)

    def _raylet_backlog_room_locked(self) -> bool:
        """Lock held.  Any raylet with queued-lease headroom?"""
        depth = GLOBAL_CONFIG.raylet_lease_backlog
        if depth <= 0:
            return False
        for node in self.nodes.values():
            if node.schedulable() and node.raylet_conn is not None \
                    and node.queued_lease_count() < depth:
                return True
        return False

    # Consecutive unplaceable specs tolerated per scan before giving up
    # until the next pump.  Without a cutoff, a deep backlog makes every
    # pump O(backlog) and the scheduler O(n^2) under pipelined one-way
    # submission (reference analog: ClusterTaskManager keeps separate
    # schedule/dispatch/waiting queues instead of rescanning one list).
    _PUMP_MISS_CAP = 32

    def _park_on_deps(self, spec: dict) -> None:
        """Lock held.  Move a dep-waiting spec off the scan queue; it is
        promoted back by _promote_dep_waiters when its deps seal."""
        waits = set()
        for dep in spec.get("deps", ()):
            m = self.objects.get(dep)
            if m is None or m.state == PENDING:
                waits.add(dep)
        if not waits:
            self._push_pending(spec)   # raced: deps arrived already
            return
        spec["_waiting_deps"] = waits
        for dep in waits:
            self.dep_waiting.setdefault(dep, []).append(spec)

    def _promote_dep_waiters(self, oid: str, errored: bool = False) -> None:
        """Lock held.  A dep sealed (ok or error): wake parked specs."""
        specs = self.dep_waiting.pop(oid, None)
        if not specs:
            return
        for spec in specs:
            waits = spec.get("_waiting_deps")
            if waits is not None:
                waits.discard(oid)
            if spec.get("cancelled") or spec.get("_dep_failed"):
                continue
            if errored:
                spec["_dep_failed"] = True
                self._fail_task_with_dep_error(spec, oid)
            elif not waits:
                spec.pop("_waiting_deps", None)
                self._push_pending(spec)

    @staticmethod
    def _spec_class(spec: dict) -> str:
        """cpu | tpu | zero | special — the resource gate in
        _dispatch_capacity is exact only for the plain-CPU and TPU
        classes; zero-CPU and special (PG/affinity/custom-resource)
        specs bypass it (they dispatch on dimensions the cheap check
        doesn't model)."""
        st = spec.get("scheduling_strategy")
        if isinstance(st, dict) or spec.get("resources"):
            return "special"
        if spec.get("num_tpus"):
            return "tpu"
        if float(spec.get("num_cpus", 1)) <= 0:
            return "zero"
        return "cpu"

    def _push_pending(self, spec: dict) -> None:
        """Lock held.  All pending-queue traffic goes through these
        helpers so _dispatch_capacity can know, in O(1), what the backlog
        is waiting for (a fruitless O(backlog) scan per pipelined submit
        was the measured control-plane bottleneck).  A spec returning to
        the global queue is no longer held by any worker: strip the
        prepush mark or a later pipeline pop would skip its push and
        strand it."""
        spec.pop("_prepushed", None)
        spec.pop("_dseq", None)
        # setdefault: a pump-miss requeue continues the same wait; only a
        # spec that actually DISPATCHED (stamp popped by
        # _observe_queue_latency) restarts the clock on re-entry (retry,
        # worker-death reschedule, actor restart)
        spec.setdefault("_enqueued_at", time.monotonic())
        self._pending_counts[self._spec_class(spec)] += 1
        self.pending_tasks.append(spec)

    def _push_pending_left(self, spec: dict) -> None:
        spec.pop("_prepushed", None)
        spec.pop("_dseq", None)
        # setdefault: a scan-skip requeue (_take_matching_pending's
        # non-matches) continues the same wait.  A requeue AFTER an
        # observed dispatch that never executed (handoff push to a
        # freshly-dead worker) restarts the clock — one logical wait
        # then shows as two shorter samples, an accepted bias during
        # worker churn (the alternative, carrying un-observation state,
        # isn't worth it for a histogram).
        spec.setdefault("_enqueued_at", time.monotonic())
        self._pending_counts[self._spec_class(spec)] += 1
        self.pending_tasks.appendleft(spec)

    def _observe_queue_latency(self, spec: dict, tier: str = "gcs") -> None:
        """A spec is leaving the scheduler queue for a worker: record the
        submit->dispatch wait (rtpu_task_queue_seconds).  pop: a retried
        or resubmitted spec re-enters the queue and re-measures.
        ``tier`` names which scheduler tier took the dispatch ("gcs"
        direct, or "raylet:<node>" for a lease grant) — carried on the
        sched: span so traces show who placed the task."""
        t = spec.pop("_enqueued_at", None)
        if t is None:
            return
        wait = time.monotonic() - t
        name = spec.get("name") or spec.get("class_name") or "task"
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_task_queue_seconds").observe(
                wait, tags={"name": name})
        tc = spec.get("trace_ctx")
        if tc and GLOBAL_CONFIG.timeline_enabled:
            # GCS leg of the request tree: one span for the scheduler
            # queue wait (submit -> dispatch), child of the submitter's
            # span, on the dedicated "gcs" timeline row.  Appended to
            # the event buffer directly — this runs under self.lock and
            # lock -> _events_lock is a legal DAG edge; an RPC here
            # would be blocking work under the global lock.
            from ray_tpu.util import tracing as _tracing
            ev = _tracing.span_event(
                f"sched:{name}", _tracing.SpanContext.from_dict(tc),
                t0=time.time() - wait, dur=wait, cat="sched",
                pid="gcs", tid=0, task_id=spec.get("task_id"),
                tier=tier)
            if ev is not None:
                with self._events_lock:
                    self.events.append(ev)

    def _pop_pending(self) -> dict:
        spec = self.pending_tasks.popleft()
        self._pending_counts[self._spec_class(spec)] -= 1
        return spec

    def _fleet_event(self, kind: str, node_id: Optional[str] = None,
                     **detail) -> None:
        """Append one fleet lifecycle event (node_added / node_draining /
        node_removed / remesh) to the bounded feed (DESIGN.md §4j).
        Callable with or without the global lock held — lock ->
        _events_lock is a legal DAG edge and the feed has its own leaf
        lock."""
        with self._events_lock:
            self._fleet_event_seq += 1
            self._fleet_events.append({
                "seq": self._fleet_event_seq, "ts": time.time(),
                "kind": kind, "node_id": node_id, **detail})

    def _dispatch_capacity(self) -> bool:
        """Lock held.  Cheap over-approximation of "could anything dispatch
        right now?" — when False, the scan below is guaranteed fruitless
        for the cpu/tpu spec classes (no free resources, or no idle
        worker / spawn headroom / piggyback room), so the pump returns
        without touching the backlog.  zero-CPU and special specs bypass
        the resource gate.  Every event that CREATES capacity (task_done,
        worker death/idle, node add, PG ready, resource release) already
        triggers its own pump, and the monitor loop force-pumps every
        0.5s as a predicate-bug safety net."""
        pc = self._pending_counts
        # resource gate: scan misses come from node.fits() — skip the scan
        # when the backlog's resource classes have no free resources
        if not (pc["special"] or pc["zero"]):
            # > 0, not >= 1: fits() admits fractional requests (0.5-CPU
            # actors), so any sliver of free CPU makes the scan worthwhile
            cpu_ok = pc["cpu"] and any(
                n.schedulable() and n.resources_avail.get("CPU", 0) > 0
                for n in self.nodes.values())
            tpu_ok = pc["tpu"] and any(
                n.schedulable() and n.resources_avail.get("TPU", 0) > 0
                for n in self.nodes.values())
            if not (cpu_ok or tpu_ok):
                # a raylet's queued-lease backlog can still absorb
                # plain-CPU specs even with zero free resources
                if not (pc["cpu"] and self._raylet_backlog_room_locked()):
                    return False
        return self._worker_capacity(
            starting_is_capacity=False, piggyback_is_capacity=True,
            count_pending_actors=True,
            tpu_headroom=bool(pc["tpu"] or pc["special"]))

    def _worker_capacity(self, *, starting_is_capacity: bool,
                         piggyback_is_capacity: bool,
                         count_pending_actors: bool,
                         tpu_headroom: bool) -> bool:
        """Lock held.  The ONE worker/node capacity scan, parameterized by
        what counts as capacity (pump gate vs prepush gate — their rules
        differ but the tallies must not drift)."""
        depth = GLOBAL_CONFIG.worker_pipeline_depth
        counts: Dict[str, List[int]] = {}
        for node in self.nodes.values():
            if node.schedulable() and node.idle_workers:
                return True
            if node.schedulable() and node.raylet_conn is not None:
                # raylet nodes schedule by grant: free ledger resources
                # (or backlog room, when queuing counts as capacity) ARE
                # dispatch capacity — no head-side idle worker needed
                if node.resources_avail.get("CPU", 0) > 0:
                    return True
                if tpu_headroom and node.resources_avail.get("TPU", 0) > 0:
                    return True
                if piggyback_is_capacity \
                        and GLOBAL_CONFIG.raylet_lease_backlog > 0 \
                        and node.queued_lease_count() \
                        < GLOBAL_CONFIG.raylet_lease_backlog:
                    return True
        for w in self.workers.values():
            if w.blocked or w.state == "dead":
                continue
            if w.state == "starting" and starting_is_capacity:
                # a slot is about to open: booting workers count against
                # the spawn cap but ARE imminent parallel capacity
                return True
            if w.state in ("starting", "idle", "busy"):
                c = counts.setdefault(w.node_id, [0, 0])
                c[1 if w.tpu_capable else 0] += 1
            if (piggyback_is_capacity and w.state == "busy"
                    and w.actor_id is None and len(w.pipeline) < depth):
                return True  # piggyback room
        pending_actors = 0
        if count_pending_actors:
            pending_actors = sum(1 for a in self.actors.values()
                                 if a.state in (A_PENDING, A_RESTARTING))
        for node in self.nodes.values():
            if not node.alive or node.is_remote:
                continue
            c = counts.get(node.node_id, [0, 0])
            cap = GLOBAL_CONFIG.num_workers_per_node or \
                int(max(1, node.resources_total.get("CPU", 1)))
            if c[0] < cap + pending_actors:
                return True
            if tpu_headroom and c[1] < GLOBAL_CONFIG.tpu_workers_per_node:
                return True
        return False

    def _pump_locked(self, force: bool = False) -> None:
        if not force and self.pending_tasks and not self._dispatch_capacity():
            self.cv.notify_all()
            return
        # Lease grants buffered per raylet node for this whole pump and
        # flushed as ONE lease_grant frame each (bulk claims, §4i) — the
        # try/finally covers the capacity early-returns below.
        grants: Dict[str, List[dict]] = {}
        try:
            self._pump_scan_locked(force, grants)
        finally:
            self._flush_lease_grants_locked(grants)

    def _pump_scan_locked(self, force: bool,
                          grants: Dict[str, List[dict]]) -> None:
        # The miss budget is for the WHOLE pump (not per pass): a typical
        # capacity event frees room for one task — one dispatch plus a
        # bounded tail of unplaceable specs, not O(backlog) rescans.
        misses = 0
        progressed = True
        while progressed:
            progressed = False
            for _ in range(len(self.pending_tasks)):
                # prepush (_take_matching_pending) consumes from the same
                # deque mid-scan: the range() above is only an upper bound
                if misses >= self._PUMP_MISS_CAP or not self.pending_tasks:
                    break
                spec = self._pop_pending()
                if spec.get("cancelled"):
                    continue
                status = self._deps_status(spec)
                if status.startswith("error:"):
                    dep = status.split(":", 1)[1]
                    self._fail_task_with_dep_error(spec, dep)
                    progressed = True
                    continue
                if status == "waiting":
                    self._park_on_deps(spec)
                    continue
                req = self._task_resources(spec)
                st = spec.get("scheduling_strategy")
                pg_claim = None
                if isinstance(st, dict) and st.get("type") == "placement_group":
                    node, pg_claim = self._pick_pg_node(spec, req)
                else:
                    node = self._pick_node(spec, req)
                if node is None:
                    if self._grant_backlog_locked(spec, req, grants):
                        # queued lease on a raylet whose running chain it
                        # can inherit — leaves the head's queue NOW
                        progressed = True
                        misses = 0
                        continue
                    self._push_pending(spec)
                    misses += 1
                    continue
                if node.raylet_conn is not None:
                    # raylet node (§4i): debit the ledger and GRANT; the
                    # raylet owns intra-node worker assignment.  Buffered
                    # — one lease_grant frame per node per pump.
                    if pg_claim is not None:
                        pg, i = pg_claim
                        for k, v in req.items():
                            pg.bundle_avail[i][k] = \
                                pg.bundle_avail[i].get(k, 0.0) - v
                        spec["_pg_claim"] = (pg.pg_id, i)
                    else:
                        node.acquire(req)
                    spec["_req"] = req
                    spec["_node"] = node.node_id
                    spec["_started_at"] = time.monotonic()
                    self._observe_queue_latency(
                        spec, tier=f"raylet:{node.node_id[:8]}")
                    node.leases_out[spec["task_id"]] = spec
                    self.running[spec["task_id"]] = (
                        f"raylet:{node.node_id[:8]}", spec)
                    grants.setdefault(node.node_id, []).append(spec)
                    progressed = True
                    misses = 0
                    continue
                need_tpu = req.get("TPU", 0) > 0
                worker = self._idle_worker_on(node, need_tpu)
                if worker is None:
                    spawned = False
                    if node.is_remote:
                        # the NodeAgent owns that host's worker pool; wait
                        # for one of its workers to go idle
                        pass
                    elif need_tpu:
                        # TPU workers have their own cap: concurrent jax
                        # inits would fight over the same chips, so one
                        # device-holding worker per node (its actor/tasks
                        # own all the node's declared chips)
                        if self._count_node_workers(node, tpu=True) < \
                                GLOBAL_CONFIG.tpu_workers_per_node:
                            self._spawn_worker(node.node_id, tpu=True)
                            spawned = True
                    else:
                        # plain cap = node CPU count (min 1)
                        cap = int(max(1, node.resources_total.get("CPU", 1)))
                        cap = GLOBAL_CONFIG.num_workers_per_node or cap
                        if self._count_node_workers(node, tpu=False) < cap + len(
                                [a for a in self.actors.values()
                                 if a.state in (A_PENDING, A_RESTARTING)]):
                            self._spawn_worker(node.node_id, tpu=False)
                            spawned = True
                    # lease piggyback is the LAST resort: only once the
                    # pool is at its cap AND nothing is mid-spawn — queuing
                    # onto a busy worker while capacity exists (or is
                    # coming up) would serialize work the scheduler should
                    # parallelize (e.g. concurrent long-running trials)
                    starting = any(
                        ws.state == "starting"
                        and ws.tpu_capable == need_tpu
                        and ws.node_id == node.node_id
                        for ws in self.workers.values())
                    if not spawned and not starting and pg_claim is None \
                            and not spec.get("is_actor_creation"):
                        tgt = self._piggyback_worker(node, req, need_tpu)
                        if tgt is not None:
                            # leaving the queue for a worker's pipeline:
                            # observe now, or a later retry would inherit
                            # the stale stamp and record submit-to-
                            # SECOND-dispatch as queue wait
                            self._observe_queue_latency(spec)
                            tgt.pipeline.append(spec)
                            progressed = True
                            misses = 0
                            continue
                    self._push_pending(spec)
                    misses += 1
                    continue
                # dispatch
                if pg_claim is not None:
                    pg, i = pg_claim
                    for k, v in req.items():
                        pg.bundle_avail[i][k] = pg.bundle_avail[i].get(k, 0.0) - v
                    spec["_pg_claim"] = (pg.pg_id, i)
                else:
                    node.acquire(req)
                spec["_req"] = req
                spec["_node"] = node.node_id
                spec["_started_at"] = time.monotonic()
                self._observe_queue_latency(spec)
                worker.state = "busy"
                worker.current_task = spec
                self.running[spec["task_id"]] = (worker.worker_id, spec)
                kind = ("create_actor" if spec.get("is_actor_creation")
                        else "execute_task")
                # prepush: same-shape dep-ready backlog rides THIS dispatch
                # message and inherits the lease task-by-task — no push,
                # no pump, no scan per follow-on task (reference: leased
                # workers stay saturated without re-entering the scheduler)
                queued: List[dict] = []
                if kind == "execute_task" and not worker.pipeline \
                        and self._spec_class(spec) == "cpu" \
                        and self._pending_counts["cpu"] \
                        and not self._parallel_capacity():
                    depth = GLOBAL_CONFIG.worker_pipeline_depth
                    worker.dseq += 1
                    while len(queued) < depth:
                        extra = self._take_matching_pending(req)
                        if extra is None:
                            break
                        extra["_prepushed"] = True
                        extra["_dseq"] = worker.dseq
                        queued.append(extra)
                    worker.pipeline.extend(queued)
                from ray_tpu._private import flight_recorder
                if flight_recorder.enabled():
                    flight_recorder.record(
                        "dispatch",
                        f"{spec['task_id'][:16]}->{worker.worker_id[:8]} "
                        f"{kind} queued={len(queued)}")
                if not worker.push({"kind": kind, "spec": spec,
                                    "dseq": worker.dseq,
                                    "queued": queued}):
                    # push failed: worker died between idle and now
                    self._handle_worker_death(worker)
                    self._push_pending(spec)
                    continue
                progressed = True
                misses = 0
                # this dispatch may have consumed the last capacity: stop
                # scanning instead of burning the miss budget on a backlog
                # that can no longer place anything
                if not force and self.pending_tasks and \
                        not self._dispatch_capacity():
                    self.cv.notify_all()
                    return
            self.cv.notify_all()

    def _grant_backlog_locked(self, spec: dict, req: Dict[str, float],
                              grants: Dict[str, List[dict]]) -> bool:
        """Lock held.  No node fits the spec right now: queue it as an
        unfunded lease (``_lease_q``) on the raylet with the shallowest
        local queue, bounded by ``raylet_lease_backlog`` per node — the
        node-scoped generalization of worker_pipeline_depth.  The
        raylet starts queued leases on idle workers (pool-bounded local
        CPU oversubscription of the ledger) or by inheriting a
        finishing same-shape task's claim; the fund/return frames
        reconcile the accounting either way.  Only prepush-safe
        plain-CPU specs ride this (same constraints as
        _take_matching_pending)."""
        depth = GLOBAL_CONFIG.raylet_lease_backlog
        if depth <= 0:
            return False
        if (self._spec_class(spec) != "cpu"
                or spec.get("is_actor_creation")
                or (spec.get("scheduling_strategy") or "DEFAULT") != "DEFAULT"
                or spec.get("runtime_env")):
            return False
        best = None
        best_q = depth
        for node in self.nodes.values():
            if not node.schedulable() or node.raylet_conn is None:
                continue
            queued = node.queued_lease_count()
            if queued < best_q:
                best, best_q = node, queued
        if best is None:
            return False
        node = best
        spec["_lease_q"] = True
        # shape marker ONLY (the raylet matches handoffs / the head
        # funds on it); never _req — a queued lease holds no funded
        # claim, and _release_task_resources must no-op on it
        spec["_lease_shape"] = dict(req)
        self._observe_queue_latency(
            spec, tier=f"raylet:{node.node_id[:8]}")
        node.leases_out[spec["task_id"]] = spec
        self.running[spec["task_id"]] = (
            f"raylet:{node.node_id[:8]}", spec)
        grants.setdefault(node.node_id, []).append(spec)
        return True

    def _flush_lease_grants_locked(self,
                                   grants: Dict[str, List[dict]]) -> None:
        """Lock held.  Ship this pump's grant buffers, one frame per
        raylet (push rides lock → raylet_conn_lock, a legal DAG edge
        like worker task pushes).  A push failure means the channel died
        between pick and flush: undo the ledger and requeue."""
        if not grants:
            return
        from ray_tpu._private import flight_recorder
        for node_id, specs in grants.items():
            node = self.nodes.get(node_id)
            ok = node is not None and node.push_raylet(
                {"kind": "lease_grant", "rid": None,
                 "epoch": node.raylet_epoch, "specs": specs})
            if flight_recorder.enabled():
                flight_recorder.record(
                    "lease_grant",
                    f"{node_id[:8]} n={len(specs)} ok={ok}")
            if ok:
                if GLOBAL_CONFIG.metrics_enabled:
                    mcat.get("rtpu_raylet_leases_total").inc(
                        len(specs), tags={"event": "granted"})
                continue
            for spec in specs:
                if node is not None:
                    node.leases_out.pop(spec["task_id"], None)
                self.running.pop(spec["task_id"], None)
                self._release_task_resources(spec)
                spec.pop("_lease_q", None)
                spec.pop("_lease_shape", None)
                self._push_pending_left(spec)
        grants.clear()

    def _release_task_resources(self, spec: dict) -> None:
        req = spec.pop("_req", None)
        node_id = spec.pop("_node", None)
        pg_claim = spec.pop("_pg_claim", None)
        if spec.pop("_cpu_released", None) and req:
            req = dict(req)
            req.pop("CPU", None)  # already released at task_blocked time
        if pg_claim is not None:
            pg, i = self.pgs.get(pg_claim[0]), pg_claim[1]
            if pg is not None:
                for k, v in (req or {}).items():
                    pg.bundle_avail[i][k] = pg.bundle_avail[i].get(k, 0.0) + v
        elif req and node_id in self.nodes:
            self.nodes[node_id].release_res(req)

    def _release_deps(self, spec: dict) -> None:
        """Drop the scheduler's hold on arg objects once the task is terminal."""
        if spec.get("_deps_released"):
            return
        spec["_deps_released"] = True
        for dep in list(spec.get("deps", ())) + list(spec.get("borrows", ())):
            self._decref(dep)

    @staticmethod
    def _count_task_terminal(state: str) -> None:
        """rtpu_tasks_total: counted HERE (the one authority on terminal
        task states) so worker- and owner-side views can never double
        count."""
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_tasks_total").inc(tags={"state": state})

    def _fail_task_with_dep_error(self, spec: dict, dep_oid: str) -> None:
        dep_meta = self.objects[dep_oid]
        self._count_task_terminal("dep_error")
        for oid in spec["return_ids"]:
            self._seal_error(oid, dep_meta.data)
        if spec.get("is_actor_creation"):
            # surface the dep error as the actor's creation error
            a = self.actors.get(spec["actor_id"])
            if a is not None and a.state != A_DEAD:
                a.state = A_DEAD
                a.death_reason = "actor constructor dependency failed"
                a.spec["_creation_error"] = dep_meta.data
                if a.name:
                    self.named_actors.pop((a.namespace, a.name), None)
                    self._repl_record("named", a.namespace, a.name, None)
                self._repl_actor_locked(a)
        self._release_deps(spec)

    def _fail_task(self, spec: dict, err: BaseException) -> None:
        from ray_tpu._private.serialization import serialize_to_bytes
        # user-initiated cancellation is not a system failure — it must
        # not move an operator's sys_error alert rate
        self._count_task_terminal(
            "cancelled" if isinstance(err, exc.TaskCancelledError)
            else "sys_error")
        data = serialize_to_bytes(err)[0]
        for oid in spec["return_ids"]:
            self._seal_error(oid, data)
        self._release_deps(spec)

    # ------------------------------------------------------------- worker mgmt
    def _handle_worker_death(self, w: WorkerState) -> None:
        """Lock held. Failure handling per SURVEY.md §5.3."""
        if w.state == "dead":
            return
        logger.info("worker death %s pid=%s node=%s state=%s actor=%s task=%s",
                    w.worker_id, w.pid, (w.node_id or "")[:8], w.state,
                    w.actor_id, (w.current_task or {}).get("task_id"))
        w.state = "dead"
        self.dead_clients.add(w.worker_id)
        if self.slab is not None and not self._shutdown:
            self.slab.reap_dead()  # free half-written slab objects it left
        node = self.nodes.get(w.node_id)
        if node is not None:
            node.workers.discard(w.worker_id)
        # release refs held by this client; close its ledger so a late
        # coalesced add_ref can't resurrect it as a forever-pinned orphan
        self._close_ledger_locked(w.worker_id)
        for oid, n in self.client_refs.pop(w.worker_id, {}).items():
            self._decref(oid, n)
        spec = w.current_task
        w.current_task = None
        # queued (never-started) pipeline tasks just reschedule — no retry
        # budget consumed
        while w.pipeline:
            qspec = w.pipeline.popleft()
            if not qspec.get("cancelled"):
                self._push_pending_left(qspec)
        if w.actor_id is not None:
            self._actor_worker_died(w.actor_id)
        elif spec is not None and spec.get("is_actor_creation"):
            # died mid-__init__, before actor_ready assigned w.actor_id:
            # route through the actor FSM so max_restarts is honored
            self._release_task_resources(spec)
            self.running.pop(spec["task_id"], None)
            a = self.actors.get(spec["actor_id"])
            if a is not None:
                a.death_reason = "worker died during actor creation"
                self._actor_worker_died(a.actor_id)
            spec = None
        if spec is not None:
            self._release_task_resources(spec)
            self.running.pop(spec["task_id"], None)
            retries = spec.get("max_retries", GLOBAL_CONFIG.task_default_max_retries)
            attempts = spec.get("attempt", 0)
            oom = spec.pop("_oom_killed", False)
            if not spec.get("is_actor_creation") and (retries < 0 or attempts < retries):
                spec = dict(spec)
                spec["attempt"] = attempts + 1
                logger.info("retrying task %s (attempt %d)%s",
                            spec["task_id"], spec["attempt"],
                            " after OOM kill" if oom else "")
                self._push_pending(spec)
            elif not spec.get("is_actor_creation"):
                if oom:
                    self._fail_task(spec, exc.OutOfMemoryError(
                        f"task {spec.get('name', spec['task_id'])} killed "
                        f"by the memory monitor: node memory usage "
                        f"exceeded the configured threshold "
                        f"(RTPU_MEMORY_USAGE_THRESHOLD)"))
                else:
                    self._fail_task(spec, exc.WorkerCrashedError(
                        f"worker {w.worker_id} (pid {w.pid}) died running "
                        f"{spec.get('name', spec['task_id'])}"))
        self.cv.notify_all()

    def _actor_worker_died(self, actor_id: str) -> None:
        a = self.actors.get(actor_id)
        if a is None or a.state == A_DEAD:
            return
        # actor-creation resources are held for the actor's lifetime;
        # give them back now that the process is gone
        self._release_task_resources(a.spec)
        if a.restarts_left != 0 and not a.spec.get("_killed"):
            a.restarts_left = max(-1, a.restarts_left - 1) if a.restarts_left > 0 else -1
            a.state = A_RESTARTING
            a.incarnation += 1
            a.addr = None
            a.worker_id = None
            respec = {k: v for k, v in a.spec.items() if not k.startswith("_")}
            respec["attempt"] = respec.get("attempt", 0) + 1
            a.spec = respec
            self._push_pending(respec)
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_actor_restarts_total").inc(
                    tags={"class": respec.get("class_name", "Actor")})
            logger.info("restarting actor %s (incarnation %d)", actor_id, a.incarnation)
        else:
            a.state = A_DEAD
            a.death_reason = a.death_reason or "worker died"
            if a.name:
                self.named_actors.pop((a.namespace, a.name), None)
                self._repl_record("named", a.namespace, a.name, None)
        self._repl_actor_locked(a)
        # restarts_left / liveness changed: keep the snapshot current so a
        # head restart doesn't resurrect a dead actor or reset its budget
        # (just sets the writer thread's event; safe under cv)
        self._persist_durable()

    def _sweep_dead_metrics(self) -> None:
        """Bound the ``__metrics__/`` KV plane without needing a reader:
        collect_cluster() reaps on scrape, but an unscraped cluster
        churning workers must not accumulate one snapshot per dead
        process forever.  Dead publishers' snapshots survive the same
        grace window the collector honors (their shutdown flush stays
        readable), then go.  Ages by HEAD-side receipt time
        (_metrics_key_seen), not the payload's publisher-host wall clock
        — cross-host skew larger than the grace must not reap a dying
        worker's final flush instantly."""
        from ray_tpu.util.metrics import DEAD_SNAPSHOT_GRACE_S
        with self.lock:
            live = {w.worker_id for w in self.workers.values()
                    if w.state != "dead"}
        with self._kv_lock:
            ns = self.kv.get("default")
            if not ns:
                return
            now = time.monotonic()
            # iterate the receipt index, not the namespace: the sweep
            # must cost O(#publishers), not an O(|kv|) scan under the
            # global lock every minute (every metrics key passes through
            # _h_kv_put, and restores strip the prefix, so the index is
            # complete)
            for key, seen in list(self._metrics_key_seen.items()):
                if key.split("/", 1)[1] in live:
                    continue
                if now - seen > DEAD_SNAPSHOT_GRACE_S:
                    ns.pop(key, None)
                    self._metrics_key_seen.pop(key, None)
            # __profile__/ receipts get the same KV hygiene; the
            # ProfileStore's windowed HISTORY for the dead process
            # stays queryable (bounded by its own rings) — only the
            # raw KV payload is reaped
            for key, seen in list(self._profile_key_seen.items()):
                if key.split("/", 1)[1] in live:
                    continue
                if now - seen > DEAD_SNAPSHOT_GRACE_S:
                    ns.pop(key, None)
                    self._profile_key_seen.pop(key, None)

    def _monitor_loop(self) -> None:
        from ray_tpu._private.memory_monitor import MemoryMonitor
        mem_monitor = MemoryMonitor(self)
        last_pump = 0.0
        while not self._shutdown:
            time.sleep(0.1)
            self._restore_grace_check()
            mem_monitor.maybe_kill(time.monotonic())
            # free rc-0-at-seal objects whose grace expired with no
            # add_refs having landed (see _seal_object)
            if self._graceful_free:
                now = time.monotonic()
                with self.cv:
                    for oid in [o for o, t in self._graceful_free.items()
                                if now - t > 10.0]:
                        self._graceful_free.pop(oid, None)
                        meta = self.objects.get(oid)
                        if meta is not None and meta.refcount <= 0 \
                                and meta.state != PENDING:
                            self._decref(oid, 0)
            # unconditional periodic pump: the _PUMP_MISS_CAP scan cutoff
            # plus queue rotation means a placeable spec deep behind
            # unplaceable ones is only reached across several pumps — and
            # with nothing running there may be no event to trigger one
            now = time.monotonic()
            if now - last_pump > 0.5 and self.pending_tasks:
                last_pump = now
                self._pump(force=True)  # liveness even if the capacity
                # predicate is ever wrong for an exotic spec shape
            dead: List[WorkerState] = []
            with self.lock:
                for w in self.workers.values():
                    if w.proc is not None and w.state != "dead" and w.proc.poll() is not None:
                        dead.append(w)
            if dead:
                with self.cv:
                    for w in dead:
                        logger.warning("worker %s pid=%s exited", w.worker_id, w.pid)
                        self._handle_worker_death(w)
                self._pump()
            # reap dead publishers' stale metrics snapshots server-side:
            # collect_cluster() reaps on read, but a cluster nobody
            # scrapes must not accumulate one KV snapshot per dead
            # process forever (they are excluded from durable
            # persistence, so nothing else bounds them)
            now = time.monotonic()
            if now - self._last_metrics_sweep > 60.0:
                self._last_metrics_sweep = now
                try:
                    self._sweep_dead_metrics()
                except Exception:  # noqa: BLE001 - telemetry hygiene only
                    logger.exception("metrics snapshot sweep failed")
            # the head's OWN profiler delta skips the KV hop: drain the
            # local sampler straight into the store on the same cadence
            # workers publish at (§4o)
            if self._profile_store is not None and \
                    now - self._last_profile_flush > \
                    max(1.0, GLOBAL_CONFIG.metrics_export_period_s):
                self._last_profile_flush = now
                try:
                    from ray_tpu.util import profiler as profiler_mod
                    payload = profiler_mod.local_payload(
                        node_id=self.head_node_id)
                    if payload is not None:
                        self._profile_store.ingest("__head__", payload)
                except Exception:  # noqa: BLE001 - telemetry best-effort
                    logger.exception("head profile flush failed")
            # anomaly detectors over the TSDB (§4k): straggler skew +
            # SLO burn rate, results into the fleet-event feed
            if self._detectors and now - self._last_detector_check > \
                    GLOBAL_CONFIG.tsdb_detector_interval_s:
                self._last_detector_check = now
                try:
                    self._run_detectors()
                except Exception:  # noqa: BLE001 - telemetry best-effort
                    logger.exception("anomaly detectors failed")
            # fleet autopilot reflex pass (§4n): feed the fleet events
            # since the last pass through the reflex engine, then tick
            # its periodic work (undrain, forecast, standby).  No GCS
            # lock is held here; the actuator takes what it documents.
            if self._autopilot is not None and \
                    now - self._last_autopilot > \
                    GLOBAL_CONFIG.autopilot_interval_s:
                self._last_autopilot = now
                try:
                    self._tick_autopilot()
                except Exception:  # noqa: BLE001 - reflexes must not
                    logger.exception("autopilot tick failed")  # kill GCS
            # purge chunked uploads abandoned by a dead uploader
            with self.lock:
                now = time.time()
                for oid in [o for o, st in self._staging.items()
                            if now - st["ts"] > 300]:
                    st = self._staging.pop(oid)
                    try:
                        os.close(st["fd"])
                    except OSError:
                        pass
                    from ray_tpu._private.shm_store import _seg_path
                    try:
                        os.unlink(str(_seg_path(oid)))
                    except OSError:
                        pass

    # -------------------------------------------------------------- rpc server
    def _accept_loop(self) -> None:
        protocol.serve_accept_loop(self._listener,
                                   lambda: self._shutdown,
                                   self._serve_conn, "gcs-serve-conn")

    def _serve_conn(self, conn) -> None:
        from ray_tpu._private import flight_recorder, wire
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.util import tracing as _tracing
        client_id: Optional[str] = None
        ver = 0  # negotiated wire version for THIS connection
        # Codec mirroring: a peer that sends rtmsg frames may not speak
        # pickle at all (the C client, any polyglot worker) — its replies
        # must come back rtmsg even for hot kinds.  Pickle-speaking peers
        # keep the C-speed pickle reply on hot kinds.
        peer_rtmsg = False
        # Per-connection refcount coalescing queue: consecutive
        # refcount-plane oneways (add_ref/add_refs/release/release_batch/
        # release_all) buffer here and apply as ONE batch under ONE
        # global-lock acquisition the moment the connection goes quiet or
        # a non-refcount frame arrives (stream order preserved) — instead
        # of one lock acquisition per oneway.
        ref_buf: List[Tuple[str, dict]] = []
        try:
            while not self._shutdown:
                try:
                    if ref_buf and not conn.poll(0.0):
                        # connection went quiet mid-burst: apply now (a
                        # lone release must not wait for a next frame)
                        self._drain_ref_ops(ref_buf)
                    # rtlint: blocks-ok(parks between a client's rpcs;
                    # client death EOFs the channel and the finally arm
                    # drains buffered ref ops — peer liveness is the
                    # deadline, per-conn thread so nothing else stalls)
                    msg, seen_ver, seen_codec = wire.conn_recv_ex(conn)
                    peer_rtmsg = seen_codec == wire._CODEC_RTMSG
                except (EOFError, OSError):
                    break
                except wire.WireError as e:
                    logger.warning("undecodable frame: %s", e)
                    break
                kind = msg.get("kind")
                rid = msg.get("rid")
                if flight_recorder.enabled():
                    flight_recorder.record(
                        "frame", f"{kind} rid={rid} "
                                 f"client={str(client_id)[:8]}")
                # wire-propagated span context: pop the optional trace
                # field BEFORE any dispatch path (handlers never see an
                # alien key); adopted only around the dispatch below so
                # it cannot leak onto this thread's next frame.  The
                # field only arrives on >= PROTO_TRACE conns.
                _ctx = _tracing.extract_wire_trace(msg)
                if rid is None and kind in wire.REF_KINDS and \
                        (ver > 0 or GLOBAL_CONFIG.proto_min_version == 0):
                    # (legacy peers on a version-fenced server fall
                    # through so the fence below still rejects them)
                    ref_buf.append((kind, msg))
                    if len(ref_buf) < 256:
                        continue  # poll-gated drain at loop top
                    self._drain_ref_ops(ref_buf)
                    continue
                if ref_buf:
                    # a non-refcount frame follows buffered refcount ops:
                    # apply them first (per-connection FIFO)
                    self._drain_ref_ops(ref_buf)
                if kind == "__proto_hello__":
                    # version negotiation (wire.py): reply at the agreed
                    # version; every later frame on this conn rides it
                    try:
                        ver = wire.negotiate_version(
                            msg.get("versions", [0]),
                            GLOBAL_CONFIG.proto_min_version)
                        reply = {"rid": rid, "error": None, "proto": ver}
                    except wire.ProtocolVersionError as e:
                        reply = {"rid": rid, "error": dumps_call(
                            ConnectionError(str(e)))}
                    try:
                        wire.conn_send(conn, reply, ver)
                    except (OSError, ValueError):
                        break
                    continue
                if kind == "attach_task_conn":
                    self._attach_task_conn(msg["worker_id"], conn,
                                           msg.get("reattach"))
                    return  # this thread becomes the push-channel reader
                if kind == "attach_worker_ctl":
                    self._attach_worker_ctl(msg["worker_id"], conn)
                    return  # thread parks until the worker disconnects
                if kind == "agent_attach":
                    self._attach_agent_conn(msg["node_id"], conn)
                    return  # thread parks until the agent disconnects
                if kind == "raylet_attach":
                    # lease channel (DESIGN.md §4i): version-fenced — a
                    # conn that never negotiated >= PROTO_RAYLET cannot
                    # carry lease frames (old peers never see them)
                    if ver < wire.PROTO_RAYLET:
                        break
                    self._attach_raylet_conn(msg["node_id"], conn, ver)
                    return  # thread becomes the lease-channel reader
                if kind == "repl_attach":
                    # warm-standby replication stream (DESIGN.md §4l):
                    # version-fenced like the lease channel; the hub's
                    # drain thread owns the conn from here (snapshot
                    # bootstrap + WAL streaming + heartbeats)
                    if ver < wire.PROTO_REPL or self._repl_hub is None:
                        break
                    self._repl_hub.adopt_standby(conn)
                    conn = None  # ownership transferred to the hub's
                    return       # drain thread; finally must not close
                if self._fenced and kind not in _FENCED_OK_KINDS:
                    # a promoted standby owns the ledger (higher epoch
                    # seen): drop the conn instead of erroring the call
                    # — the client's reconnect path re-dials gcs.sock,
                    # which the new head re-bound (DESIGN.md §4l)
                    logger.warning("fenced head dropping %s conn "
                                   "(client %s)", kind,
                                   str(client_id)[:8])
                    break
                if seen_ver == 0 and ver == 0 \
                        and GLOBAL_CONFIG.proto_min_version > 0:
                    # un-negotiated legacy peer on a version-fenced server.
                    # (attach kinds above are exempt: they are one-shot
                    # messages that CONVERT the conn into a server-push
                    # channel — in-cluster senders from this same build,
                    # not the cross-version clients the fence is for)
                    err = dumps_call(ConnectionError(
                        f"wire protocol >= v"
                        f"{GLOBAL_CONFIG.proto_min_version} required "
                        f"(send __proto_hello__)"))
                    try:
                        wire.conn_send(conn, {"rid": rid, "error": err}, 0)
                    except (OSError, ValueError):
                        pass
                    break
                if client_id is None and "client_id" in msg:
                    client_id = msg["client_id"]
                dedup = msg.get("_dedup")
                key = (msg.get("client_id"), dedup) if dedup else None
                if key is not None:
                    replay = self._dedup_begin(key)
                    if replay is not None:
                        # retry of an already-applied mutation (channel
                        # broke after apply, before the reply): replay the
                        # recorded reply, don't double-apply
                        if rid is not None:
                            try:
                                wire.conn_send(conn, {"rid": rid, **replay},
                                               ver, kind in wire._HOT_KINDS
                                               and not peer_rtmsg)
                            except (OSError, ValueError):
                                break
                        continue
                reply = None
                try:
                    if _ctx is None:
                        resp = self._dispatch(kind, msg)
                    else:
                        _tok = _tracing.adopt(_ctx)
                        try:
                            resp = self._dispatch(kind, msg)
                        finally:
                            _tracing.restore(_tok)
                    reply = {"error": None, **(resp or {})}
                except Exception as e:  # noqa: BLE001 - report to caller
                    try:
                        reply = {"error": dumps_call(e)}
                    except Exception:  # noqa: BLE001 - unpicklable error
                        reply = {"error": dumps_call(
                            exc.RaySystemError(repr(e)))}
                    if rid is None:
                        logger.exception("one-way rpc %s failed", kind)
                finally:
                    if key is not None:
                        self._dedup_commit(key, reply)
                if rid is not None:
                    try:
                        wire.conn_send(conn, {"rid": rid, **reply}, ver,
                                       kind in wire._HOT_KINDS
                                       and not peer_rtmsg)
                    except (OSError, ValueError):
                        break
        finally:
            # a client that flushed releases and closed must not lose them
            try:
                self._drain_ref_ops(ref_buf)
            except Exception:  # noqa: BLE001 - shutdown path
                logger.exception("final ref-op drain failed")
            try:
                if conn is not None:  # None: handed off to the repl hub
                    conn.close()
            except OSError:
                pass

    def _dedup_begin(self, key) -> Optional[dict]:
        """Returns the recorded reply for a retried mutation, or None when
        this thread should apply it.  The pending marker makes lookup
        atomic with apply: a retry arriving while the original dispatch is
        still blocked (e.g. on gcs.lock) must WAIT for its outcome, not
        miss the cache and double-apply."""
        while True:
            with self._dedup_lock:
                cached = self._dedup_cache.get(key)
                if cached is not None:
                    return cached
                ev = self._dedup_pending.get(key)
                if ev is None:
                    self._dedup_pending[key] = threading.Event()
                    return None
            from ray_tpu._private import lock_watchdog
            with lock_watchdog.bounded_block("gcs.dedup_wait"):
                won = ev.wait(30.0)
            if not won:
                # original thread wedged: degrade to at-least-once rather
                # than hanging the retry forever
                return None

    def _dedup_commit(self, key, reply: Optional[dict]) -> None:
        with self._dedup_lock:
            if reply is not None:
                self._dedup_cache[key] = reply
                while len(self._dedup_cache) > 8192:
                    self._dedup_cache.popitem(last=False)
            ev = self._dedup_pending.pop(key, None)
        if ev is not None:
            ev.set()

    def _attach_agent_conn(self, node_id: str, conn) -> None:
        """Park on the NodeAgent's control connection; its EOF means the
        agent (and its host) is gone — remove the node so pinned work
        fails over instead of queueing against a ghost forever."""
        logger.info("node agent attached for node %s", node_id[:8])
        while not self._shutdown:
            try:
                # rtlint: blocks-ok(parks for the agent's lifetime; the
                # EOF on agent/host death is the signal this loop exists
                # to catch — it triggers node removal below)
                conn.recv()
            except (EOFError, OSError):
                break
        if not self._shutdown:
            logger.warning("node agent for %s disconnected; removing node",
                           node_id[:8])
            try:
                self.remove_node_internal(node_id)
            except Exception:  # noqa: BLE001
                logger.exception("agent node removal failed")

    def _push_worker_ctl(self, w: WorkerState, msg: dict) -> bool:
        """Push an OOB control frame to a worker, routing via its node's
        raylet (``worker_ctl``) when the worker's channels attach there
        instead of here (raylet nodes own their workers' task/ctl conns)."""
        if w.push_ctl(msg):
            return True
        node = self.nodes.get(w.node_id)
        if node is not None and node.raylet_conn is not None:
            return node.push_raylet({"kind": "worker_ctl", "rid": None,
                                     "worker_id": w.worker_id,
                                     "msg": msg})
        return False

    # ------------------------------------------------- raylet lease channel
    def _attach_raylet_conn(self, node_id: str, conn, ver: int) -> None:
        """Serve one node's raylet lease channel (DESIGN.md §4i).  The
        conn is bidirectional: the pump pushes ``lease_grant`` blocks
        down it (push_raylet), and this thread reads the raylet's
        batched reports.  It is ALSO the node's one liveness path — EOF
        reclaims every outstanding lease and removes the node."""
        from ray_tpu._private import flight_recorder, wire
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                conn.close()
                return
            node.raylet_epoch += 1
            node.raylet_proto = ver
            with node.raylet_conn_lock:
                node.raylet_conn = conn
            node.last_heartbeat = time.monotonic()
            self.cv.notify_all()
        logger.info("raylet attached for node %s (proto v%d)",
                    node_id[:8], ver)
        self._pump()
        detached = False
        while not self._shutdown:
            try:
                # rtlint: blocks-ok(parks between raylet pushes; raylet
                # heartbeats every beat so silence longer than the
                # monitor's dead-node threshold ends in EOF/removal)
                msg, _ = wire.conn_recv(conn)
            except (EOFError, OSError, wire.WireError):
                break
            kind = msg.get("kind")
            if self._fenced:
                # a promoted standby owns the ledger: raylet reports
                # mutate actor/lease/object state, so the fence must
                # cover this channel too — drop it; the raylet's
                # upstream-EOF path re-dials gcs.sock, which the new
                # head re-bound (DESIGN.md §4l)
                logger.warning("fenced head dropping raylet channel "
                               "(node %s, frame %s)", node_id[:8], kind)
                break
            if flight_recorder.enabled():
                flight_recorder.record("raylet_frame",
                                       f"{kind} node={node_id[:8]}")
            try:
                if kind == "raylet_done_batch":
                    self._on_raylet_done_batch(node_id, msg)
                elif kind == "raylet_ref_batch":
                    self._on_raylet_ref_batch(msg)
                elif kind == "raylet_fwd":
                    self._on_raylet_fwd(node_id, msg)
                elif kind == "raylet_worker_died":
                    self._on_raylet_worker_died(msg)
                elif kind == "raylet_task_blocked":
                    self._on_raylet_blocked(node_id, msg, blocked=True)
                elif kind == "raylet_task_unblocked":
                    self._on_raylet_blocked(node_id, msg, blocked=False)
                elif kind == "raylet_heartbeat":
                    self._on_raylet_heartbeat(node_id, msg)
                elif kind == "raylet_lease_return":
                    self._on_raylet_lease_return(node_id, msg)
                elif kind == "raylet_workers":
                    self._on_raylet_workers(node_id, msg)
                elif kind == "raylet_detach":
                    detached = True
                    break
                else:
                    logger.warning("unknown raylet frame %r", kind)
            except Exception:  # noqa: BLE001 - one bad report must not
                # tear down the whole node's lease channel
                logger.exception("raylet frame failed: %s", kind)
        with self.lock:
            node = self.nodes.get(node_id)
            if node is not None:
                with node.raylet_conn_lock:
                    if node.raylet_conn is conn:
                        node.raylet_conn = None
        try:
            conn.close()
        except OSError:
            pass
        if not self._shutdown:
            log = logger.info if detached else logger.warning
            log("raylet for node %s %s; reclaiming leases and removing "
                "node", node_id[:8],
                "detached" if detached else "disconnected")
            try:
                # remove_node_internal reclaims outstanding leases first
                self.remove_node_internal(node_id)
            except Exception:  # noqa: BLE001
                logger.exception("raylet node removal failed")
            self._pump()

    def _reclaim_raylet_leases_locked(self, node: NodeState) -> None:
        """Lock held.  The node's lease channel is gone: queued leases
        (never started) re-queue free; funded leases may have been
        mid-execution, so they consume a retry attempt — the same
        contract as worker death.  Net resources return to zero."""
        leases, node.leases_out = node.leases_out, {}
        reclaimed = 0
        for tid, spec in leases.items():
            self.running.pop(tid, None)
            reclaimed += 1
            if spec.get("is_actor_creation"):
                a = self.actors.get(spec.get("actor_id"))
                if a is not None and a.state == A_ALIVE:
                    continue  # settled via actor_ready; nothing to undo
                if a is not None:
                    a.death_reason = "raylet died during actor creation"
                    # _actor_worker_died releases the creation resources
                    self._actor_worker_died(a.actor_id)
                continue
            self._release_task_resources(spec)
            if spec.get("cancelled"):
                continue
            if spec.pop("_lease_q", None):
                spec.pop("_lease_shape", None)
                self._push_pending_left(spec)  # never started: free requeue
                continue
            retries = spec.get("max_retries",
                               GLOBAL_CONFIG.task_default_max_retries)
            attempts = spec.get("attempt", 0)
            if retries < 0 or attempts < retries:
                spec2 = dict(spec)
                spec2["attempt"] = attempts + 1
                self._push_pending(spec2)
            else:
                self._fail_task(spec, exc.WorkerCrashedError(
                    f"raylet on node {node.node_id[:8]} died running "
                    f"{spec.get('name', spec['task_id'])}"))
        if reclaimed and GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_raylet_leases_total").inc(
                reclaimed, tags={"event": "reclaimed"})

    def _finish_task_ok_locked(self, spec: dict, results, w_node_id) -> None:
        """Lock held.  Seal a completed task's returns + lineage — the
        ONE ok-settlement path, shared by the direct worker channel
        (_on_task_done) and the raylet done batch."""
        for oid, res in zip(spec["return_ids"], results):
            meta = self._get_or_create_meta(oid)
            if meta.refcount <= 0 and not spec.get("is_reconstruction"):
                meta.refcount += 1  # owner's initial reference
            if res["loc"] == "shm":
                self.store.adopt(oid, res.get("size", 0))
            self._seal_object(
                oid, res["loc"], res.get("data"), res.get("size", 0),
                spec.get("_node") or w_node_id, res.get("contained", []),
                lineage_task=spec["task_id"])
        self.lineage[spec["task_id"]] = {
            k: v for k, v in spec.items() if not k.startswith("_")}
        self.lineage_order.append(spec["task_id"])
        if len(self.lineage) > self.lineage_order.maxlen:
            live = set(self.lineage_order)
            for tid in [t for t in self.lineage if t not in live]:
                self.lineage.pop(tid, None)
        self._release_deps(spec)
        self._count_task_terminal("ok")

    def _on_raylet_done_batch(self, node_id: str, msg: dict) -> None:
        """Apply one batch of lease settlements under ONE global-lock
        acquisition (the raylet-side analog of _drain_ref_ops)."""
        evs: List[dict] = []
        for entry in msg.get("entries", ()):
            if entry.get("events"):
                evs.extend(entry["events"])
        if evs:
            with self._events_lock:
                self.events.extend(evs)
        t0 = time.monotonic()
        done = handoffs = 0
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None:
                return
            for entry in msg.get("entries", ()):
                self._apply_raylet_done_locked(node, entry)
                done += 1
                if entry.get("next_task_id"):
                    handoffs += 1
            self.cv.notify_all()
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "raylet_done_batch"})
            mcat.get("rtpu_raylet_leases_total").inc(
                done, tags={"event": "done"})
            if handoffs:
                mcat.get("rtpu_raylet_leases_total").inc(
                    handoffs, tags={"event": "handoff"})
        if self.pending_tasks:
            self._pump()

    def _apply_raylet_done_locked(self, node: NodeState,
                                  entry: dict) -> None:
        tid = entry["task_id"]
        status = entry.get("status")
        spec = node.leases_out.pop(tid, None)
        if spec is None:
            # unknown lease: this head restarted between grant and done
            # (or a reclaim raced the report).  The return ids in the
            # entry are authoritative — adopt the results so the value
            # is not lost; a resubmitted copy double-sealing the same
            # ids is tolerated by the seal path.
            if status == "ok":
                for oid, res in zip(entry.get("return_ids", ()),
                                    entry.get("results") or ()):
                    meta = self._get_or_create_meta(oid)
                    if meta.refcount <= 0:
                        meta.refcount += 1
                    if res["loc"] == "shm":
                        self.store.adopt(oid, res.get("size", 0))
                    self._seal_object(
                        oid, res["loc"], res.get("data"),
                        res.get("size", 0),
                        node.node_id if res["loc"] == "remote" else None,
                        res.get("contained", []))
            return
        self.running.pop(tid, None)
        # lease handoff: the raylet already started next_task_id on this
        # claim (reference: lease reuse) — MOVE it on the ledger instead
        # of release-then-reacquire
        nxt = None
        ntid = entry.get("next_task_id")
        if ntid is not None:
            nxt = node.leases_out.get(ntid)
        if nxt is not None and not nxt.get("cancelled") \
                and "_req" in spec and "_pg_claim" not in spec \
                and nxt.pop("_lease_q", None):
            # move the claim — but NEVER from a placement-group-funded
            # spec: its claim lives on the PG bundle, not the node
            # ledger, and a plain inheritor would release against the
            # wrong pool
            nxt.pop("_lease_shape", None)
            nxt["_req"] = spec.pop("_req")
            nxt["_node"] = spec.pop("_node", None)
            nxt["_started_at"] = time.monotonic()
        else:
            self._release_task_resources(spec)
        if status == "ok":
            self._finish_task_ok_locked(spec, entry.get("results") or [],
                                        node.node_id)
        elif status == "app_error":
            retries = spec.get("max_retries", 0) \
                if spec.get("retry_exceptions") else 0
            # retries < 0 = infinite (same contract as system retries)
            if retries and (retries < 0
                            or spec.get("attempt", 0) < retries):
                spec2 = dict(spec)
                spec2["attempt"] = spec.get("attempt", 0) + 1
                self._push_pending(spec2)
            else:
                for oid in spec["return_ids"]:
                    self._seal_error(oid, entry["error"])
                self._release_deps(spec)
                self._count_task_terminal("app_error")
        elif status == "worker_died":
            retries = spec.get("max_retries",
                               GLOBAL_CONFIG.task_default_max_retries)
            attempts = spec.get("attempt", 0)
            if spec.get("cancelled"):
                pass  # cancel raced the death: already settled
            elif retries < 0 or attempts < retries:
                spec2 = dict(spec)
                spec2["attempt"] = attempts + 1
                self._push_pending(spec2)
            else:
                self._fail_task(spec, exc.WorkerCrashedError(
                    f"worker on node {node.node_id[:8]} died running "
                    f"{spec.get('name', spec['task_id'])}"))

    def _on_raylet_ref_batch(self, msg: dict) -> None:
        """Apply a raylet's netted owner-local release deltas through
        the same single-acquisition batch path as connection-coalesced
        ref oneways (_drain_ref_ops → _apply_ref_op_locked)."""
        ops = [(str(k), dict(m)) for k, m in msg.get("ops", ())]
        n = int(msg.get("netted") or len(ops))
        self._drain_ref_ops(ops)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_raylet_ref_ops_total").inc(
                n, tags={"path": "reconciled"})

    def _on_raylet_fwd(self, node_id: str, msg: dict) -> None:
        inner = msg.get("msg") or {}
        self._handle_worker_event(msg.get("worker_id"), inner)
        if inner.get("kind") == "actor_ready" and not inner.get("reattach"):
            # settle the creation lease on the SAME thread as the actor
            # linkage: a raylet death in between must never reclaim (and
            # re-run) a creation whose actor is already ALIVE/DEAD
            with self.cv:
                node = self.nodes.get(node_id)
                a = self.actors.get(inner.get("actor_id"))
                if node is not None and a is not None:
                    tid = a.spec.get("task_id")
                    node.leases_out.pop(tid, None)

    def _on_raylet_worker_died(self, msg: dict) -> None:
        with self.cv:
            w = self.workers.get(msg.get("worker_id"))
            if w is not None:
                self._handle_worker_death(w)
        self._pump()

    def _on_raylet_blocked(self, node_id: str, msg: dict,
                           blocked: bool) -> None:
        """A leased task parked in (or returned from) get() on a raylet
        node: credit/debit the CPU exactly like the direct-worker
        task_blocked path, keyed by the lease ledger instead of
        WorkerState.current_task."""
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None:
                return
            spec = node.leases_out.get(msg.get("task_id"))
            if spec is None:
                return
            cpu = (spec.get("_req") or {}).get("CPU", 0)
            if not cpu:
                return
            pg_claim = spec.get("_pg_claim")
            if blocked and not spec.get("_cpu_released"):
                spec["_cpu_released"] = True
                if pg_claim is not None:
                    pg = self.pgs.get(pg_claim[0])
                    if pg is not None:
                        avail = pg.bundle_avail[pg_claim[1]]
                        avail["CPU"] = avail.get("CPU", 0.0) + cpu
                else:
                    node.release_res({"CPU": cpu})
                self.cv.notify_all()
            elif not blocked and spec.pop("_cpu_released", None):
                if pg_claim is not None:
                    pg = self.pgs.get(pg_claim[0])
                    if pg is not None:
                        avail = pg.bundle_avail[pg_claim[1]]
                        avail["CPU"] = avail.get("CPU", 0.0) - cpu
                else:
                    node.acquire({"CPU": cpu})
        if blocked:
            self._pump()

    def _on_raylet_heartbeat(self, node_id: str, msg: dict) -> None:
        stats = dict(msg.get("stats") or {})
        age = float(msg.get("reconcile_age") or 0.0)
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                return
            node.last_heartbeat = time.monotonic()
            node.raylet_stats = stats
            node.raylet_reconcile_age = age
        if GLOBAL_CONFIG.metrics_enabled:
            sid = node_id[:8]
            mcat.get("rtpu_raylet_queue_depth").set(
                float(stats.get("queued", 0)), tags={"node": sid})
            mcat.get("rtpu_raylet_reconcile_age_seconds").set(
                age, tags={"node": sid})

    def _on_raylet_lease_return(self, node_id: str, msg: dict) -> None:
        """A raylet handing back leases it never started (idle shedding
        / clean shutdown): requeue them with no retry consumed."""
        returned = 0
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None:
                return
            for tid in msg.get("task_ids", ()):
                spec = node.leases_out.pop(tid, None)
                if spec is None:
                    continue
                self.running.pop(tid, None)
                self._release_task_resources(spec)
                spec.pop("_lease_q", None)
                spec.pop("_lease_shape", None)
                if not spec.get("cancelled"):
                    self._push_pending_left(spec)
                    returned += 1
            self.cv.notify_all()
        if returned and GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_raylet_leases_total").inc(
                returned, tags={"event": "returned"})
        self._pump()

    def _on_raylet_workers(self, node_id: str, msg: dict) -> None:
        """Post-head-restart roster re-announce: adopt the raylet's
        surviving workers onto its NEW node id (their own register_client
        reconnects may have parked them on the head node)."""
        with self.cv:
            node = self.nodes.get(node_id)
            if node is None:
                return
            for went in msg.get("workers", ()):
                wid = went.get("worker_id")
                if not wid:
                    continue
                w = self.workers.get(wid)
                if w is None:
                    w = WorkerState(wid, node_id, went.get("pid", 0))
                    self.workers[wid] = w
                else:
                    old = self.nodes.get(w.node_id)
                    if old is not None and old is not node:
                        old.workers.discard(wid)
                    w.node_id = node_id
                node.workers.add(wid)
            self.cv.notify_all()

    def _attach_worker_ctl(self, worker_id: str, conn) -> None:
        """Register a worker's out-of-band control connection (cancel /
        drop_queued / dump_stack / stop_worker reach the worker even while
        its main thread executes a task).  Best-effort: EOF here is NOT a
        death signal (the task conn is the liveness channel) — just clear
        the registration so push_ctl falls back to the task conn."""
        with self.cv:
            w = self.workers.get(worker_id)
            if w is None:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            with w.ctl_conn_lock:
                w.ctl_conn = conn
        while not self._shutdown:
            try:
                conn.recv()
            except (EOFError, OSError):
                break
        with self.lock:
            w = self.workers.get(worker_id)
        if w is not None:
            with w.ctl_conn_lock:
                if w.ctl_conn is conn:
                    w.ctl_conn = None
        try:
            conn.close()
        except OSError:
            pass

    def _attach_task_conn(self, worker_id: str, conn,
                          reattach: Optional[dict] = None) -> None:
        with self.cv:
            w = self.workers.get(worker_id)
            if w is None and reattach is not None:
                # surviving worker of a crashed head reconnecting
                # (GCS fault tolerance): rebuild its WorkerState.  Its
                # recorded node is gone with the old head — adopt it onto
                # this head's node.  proc stays None: liveness is this
                # conn's EOF (same as remote-agent workers).
                node_id = reattach.get("node_id")
                if node_id not in self.nodes:
                    node_id = self.head_node_id
                w = WorkerState(worker_id, node_id, reattach.get("pid", 0))
                self.workers[worker_id] = w
                node = self.nodes.get(node_id)
                if node is not None:
                    node.workers.add(worker_id)
            if w is None:
                conn.close()
                return
            if reattach is not None:
                # The WorkerState usually ALREADY exists here: the worker's
                # _reconnect_pool() re-registered it (state "starting")
                # before this attach arrived.  Apply the reattach metadata
                # unconditionally — before the starting→idle transition
                # below — or an actor worker would be marked idle and the
                # scheduler would dispatch a plain task into a process
                # blocked in serve_forever (and tpu_capable would be lost).
                w.tpu_capable = w.tpu_capable or bool(reattach.get("tpu"))
                if reattach.get("actor_id"):
                    # actor worker: its main thread sits in serve_forever —
                    # it must never enter the idle pool.  The follow-up
                    # actor_ready(reattach) event completes the actor
                    # linkage (addr, resources, ALIVE).
                    w.state = "actor"
                    w.actor_id = reattach["actor_id"]
                    node = self.nodes.get(w.node_id)
                    if node is not None and worker_id in node.idle_workers:
                        node.idle_workers.remove(worker_id)
                logger.info("worker %s reattached after GCS restart",
                            worker_id[:8])
            w.task_conn = conn
            if w.state == "starting":
                w.state = "idle"
                node = self.nodes.get(w.node_id)
                if node is not None:
                    node.idle_workers.append(worker_id)
            self.cv.notify_all()
        self._pump()
        # reader loop for one-way worker events
        while not self._shutdown:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            try:
                self._handle_worker_event(worker_id, msg)
            except Exception:
                logger.exception("worker event failed: %s", msg.get("kind"))
        logger.debug("task conn EOF for worker %s", worker_id)
        with self.cv:
            w = self.workers.get(worker_id)
            if w is not None and w.proc is None:
                # proc-less worker (in-process driver, or remote-agent
                # worker the head never forked): conn EOF IS the death
                # signal — there is no local pid to poll
                self._handle_worker_death(w)
        self._pump()

    # ----------------------------------------------------------- worker events
    def _handle_worker_event(self, worker_id: str, msg: dict) -> None:
        kind = msg["kind"]
        if kind == "task_done":
            self._on_task_done(worker_id, msg)
        elif kind == "actor_ready":
            self._on_actor_ready(worker_id, msg)
        elif kind == "actor_result":
            # actor method results sealed by the actor's worker
            t0 = time.monotonic()
            with self.cv:
                w = self.workers.get(worker_id)
                for oid, res in zip(msg["return_ids"], msg["results"]):
                    meta = self._get_or_create_meta(oid)
                    if res["loc"] == "error":
                        self._seal_error(oid, res["data"])
                    else:
                        if res["loc"] == "shm":
                            self.store.adopt(oid, res.get("size", 0))
                        # remote-spooled results are pinned to the holder
                        # node (P2P pulls resolve its data addr; node loss
                        # routes them to reconstruction)
                        self._seal_object(
                            oid, res["loc"], res.get("data"),
                            res.get("size", 0),
                            (w.node_id if w is not None
                             and res["loc"] == "remote" else None),
                            res.get("contained", []))
                        if res["loc"] == "remote" and w is None:
                            # holder unknown (worker record already
                            # reaped): a READY remote object with no
                            # node resolves nowhere and node-loss scans
                            # never reclaim it — mark lost NOW
                            self._mark_object_lost(
                                oid, self.objects[oid])
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                    time.monotonic() - t0, tags={"kind": "actor_result"})
            if self.pending_tasks:
                self._pump()  # tasks may be dep-waiting on these objects
        elif kind == "task_blocked":
            # reference: raylet releases the CPU while a task blocks in get().
            # Credit whichever pool the CPU was claimed from: the PG bundle
            # for placement-group tasks, the node otherwise.
            with self.cv:
                w = self.workers.get(worker_id)
                if w is not None and w.current_task is not None:
                    w.blocked = True
                    # a blocked worker can't drain its pipeline (and its
                    # queued tasks could even be what it blocks ON) —
                    # give them back to the scheduler; the worker must
                    # drop its prepushed copies or a respawned-elsewhere
                    # spec would also run here after the unblock
                    dropped = [(s["task_id"], s.get("_dseq"))
                               for s in w.pipeline if s.get("_prepushed")]
                    while w.pipeline:
                        self._push_pending_left(w.pipeline.pop())
                    if dropped:
                        w.push_ctl({"kind": "drop_queued", "pairs": dropped})
                    spec = w.current_task
                    cpu = (spec.get("_req") or {}).get("CPU", 0)
                    if cpu and not spec.get("_cpu_released"):
                        spec["_cpu_released"] = True
                        pg_claim = spec.get("_pg_claim")
                        if pg_claim is not None:
                            pg = self.pgs.get(pg_claim[0])
                            if pg is not None:
                                avail = pg.bundle_avail[pg_claim[1]]
                                avail["CPU"] = avail.get("CPU", 0.0) + cpu
                        else:
                            node = self.nodes.get(w.node_id)
                            if node is not None:
                                node.release_res({"CPU": cpu})
                        self.cv.notify_all()
            self._pump()
        elif kind == "task_unblocked":
            with self.cv:
                w = self.workers.get(worker_id)
                if w is not None:
                    w.blocked = False
                if w is not None and w.current_task is not None \
                        and w.current_task.pop("_cpu_released", None):
                    spec = w.current_task
                    cpu = (spec.get("_req") or {}).get("CPU", 0)
                    pg_claim = spec.get("_pg_claim")
                    if pg_claim is not None:
                        pg = self.pgs.get(pg_claim[0])
                        if pg is not None:
                            avail = pg.bundle_avail[pg_claim[1]]
                            avail["CPU"] = avail.get("CPU", 0.0) - cpu
                    else:
                        node = self.nodes.get(w.node_id)
                        if node is not None:
                            node.acquire({"CPU": cpu})
        elif kind == "actor_exit":
            with self.cv:
                a = self.actors.get(msg["actor_id"])
                if a is not None:
                    a.spec["_killed"] = True  # intentional exit → no restart
                    a.death_reason = "exit_actor"
        elif kind == "stack_dump":
            with self.cv:
                for req in self._stack_reqs:
                    req[worker_id] = msg["text"]
                self.cv.notify_all()
        elif kind == "log" and self.log_sink is not None:
            self.log_sink(msg["line"])
        elif kind == "profile_events":
            with self._events_lock:
                self.events.extend(msg["events"])

    def _parallel_capacity(self) -> bool:
        """Lock held.  Could another INDEPENDENT execution slot take work
        right now (idle worker, booting worker, or spawn headroom — NOT
        piggyback room)?  Prepush/refill must never serialize onto one
        lease work that could run concurrently elsewhere (e.g. two Tune
        trials).  Shares the scan with _dispatch_capacity."""
        return self._worker_capacity(starting_is_capacity=True,
                                     piggyback_is_capacity=False,
                                     count_pending_actors=False,
                                     tpu_headroom=False)

    def _take_matching_pending(self, req) -> Optional[dict]:
        """Lock held.  Pop the first dep-ready plain-CPU spec whose
        resource shape matches ``req`` (lease inheritance candidates);
        bounded probe so a mismatched backlog costs O(1)."""
        if req is None:
            return None
        skipped = []
        found = None
        for _ in range(min(8, len(self.pending_tasks))):
            spec = self._pop_pending()
            if spec.get("cancelled"):
                continue
            if (self._spec_class(spec) == "cpu"
                    and not spec.get("is_actor_creation")
                    and (spec.get("scheduling_strategy") or "DEFAULT")
                    == "DEFAULT"
                    and not spec.get("runtime_env")
                    and self._task_resources(spec) == req
                    and self._deps_status(spec) == "ready"):
                found = spec
                break
            skipped.append(spec)
        for spec in reversed(skipped):
            self._push_pending_left(spec)
        if found is not None:
            # lease inheritance / prepush: the spec leaves the queue here
            self._observe_queue_latency(found)
        return found

    def _on_task_done(self, worker_id: str, msg: dict) -> None:
        evs = msg.get("events")
        if evs:
            # timeline events ride the task_done frame (one message per
            # task, not two); buffered under their own lock
            with self._events_lock:
                self.events.extend(evs)
        t0 = time.monotonic()
        with self.cv:
            lock_waited = time.monotonic() - t0
            w = self.workers.get(worker_id)
            spec = w.current_task if w else None
            if spec is None or spec["task_id"] != msg["task_id"]:
                return
            self.running.pop(spec["task_id"], None)
            # lease handoff: a queued same-shape task inherits this task's
            # resource claim instead of release-then-reacquire (and skips
            # the pump scan entirely — the worker stays saturated)
            nxt = None
            while w.pipeline:
                cand = w.pipeline.popleft()
                if not cand.get("cancelled"):
                    nxt = cand
                    break
            if nxt is None and not w.blocked and w.state == "busy" \
                    and w.actor_id is None and "_req" in spec \
                    and not spec.get("is_actor_creation") \
                    and self._pending_counts["cpu"]:
                # refill from the backlog while the lease is still alive
                # (reference: lease reuse — the raylet keeps a leased
                # worker saturated without re-running the scheduler)
                nxt = self._take_matching_pending(spec["_req"])
            if nxt is not None and "_req" in spec:
                nxt["_req"] = spec.pop("_req")
                nxt["_node"] = spec.pop("_node")
            refill_queued: List[dict] = []
            if nxt is not None and not nxt.get("_prepushed") \
                    and not w.pipeline and self._pending_counts["cpu"] \
                    and not self._parallel_capacity():
                # refill the pipeline too, and ship it WITH nxt's push
                # below (prepushed) — one message re-saturates the worker
                depth = GLOBAL_CONFIG.worker_pipeline_depth
                w.dseq += 1
                while len(refill_queued) < depth:
                    extra = self._take_matching_pending(nxt["_req"])
                    if extra is None:
                        break
                    extra["_prepushed"] = True
                    extra["_dseq"] = w.dseq
                    refill_queued.append(extra)
                w.pipeline.extend(refill_queued)
            self._release_task_resources(spec)
            w.current_task = None
            w.blocked = False
            # store results
            if msg["status"] == "ok":
                self._finish_task_ok_locked(spec, msg["results"], w.node_id)
            elif msg["status"] == "app_error":
                retries = spec.get("max_retries", 0) if spec.get("retry_exceptions") \
                    else 0
                # retries < 0 = infinite (same contract as system retries)
                if retries and (retries < 0
                                or spec.get("attempt", 0) < retries):
                    spec2 = dict(spec)
                    spec2["attempt"] = spec.get("attempt", 0) + 1
                    self._push_pending(spec2)
                else:
                    for oid in spec["return_ids"]:
                        self._seal_error(oid, msg["error"])
                    self._release_deps(spec)
                    self._count_task_terminal("app_error")
            # next leased task, or worker back to pool
            if nxt is not None and w.state == "busy" \
                    and nxt.pop("_prepushed", None):
                # the worker already holds this spec (prepushed with the
                # dispatch message) and is running it right now
                w.current_task = nxt
                self.running[nxt["task_id"]] = (worker_id, nxt)
            elif nxt is not None and w.state == "busy":
                w.current_task = nxt
                self.running[nxt["task_id"]] = (worker_id, nxt)
                if not w.push({"kind": "execute_task", "spec": nxt,
                               "dseq": w.dseq,
                               "queued": refill_queued}):
                    # worker died between done and handoff: the task never
                    # STARTED — reschedule it without consuming its retry
                    # budget (same invariant as the queued pipeline)
                    self.running.pop(nxt["task_id"], None)
                    w.current_task = None
                    self._release_task_resources(nxt)
                    self._push_pending_left(nxt)
                    self._handle_worker_death(w)
            elif w.state == "busy":
                w.state = "idle"
                node = self.nodes.get(w.node_id)
                if node is not None and node.alive:
                    node.idle_workers.append(worker_id)
            self.cv.notify_all()
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_gcs_lock_wait_seconds").set(
                lock_waited, tags={"lock": "global"})
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "task_done"})
        if self.pending_tasks:
            # nothing queued → nothing the freed capacity could dispatch;
            # skip the scan (len() is GIL-atomic, no lock needed)
            self._pump()

    def _on_actor_ready(self, worker_id: str, msg: dict) -> None:
        with self.cv:
            a = self.actors.get(msg["actor_id"])
            w = self.workers.get(worker_id)
            if a is None or w is None:
                return
            if msg.get("reattach"):
                # surviving actor re-announcing to a restarted head: no
                # creation task to settle, no resources were acquired on
                # this GCS — re-acquire the actor-lifetime hold so the
                # node's accounting matches reality, then go ALIVE.
                if a.state == A_DEAD:
                    return
                a.state = A_ALIVE
                a.worker_id = worker_id
                a.addr = msg["addr"]
                w.state = "actor"
                w.actor_id = a.actor_id
                if a.spec.get("hold_resources", True):
                    req = self._task_resources(a.spec)
                    node = self.nodes.get(w.node_id)
                    if req and node is not None:
                        node.acquire(req)
                        a.spec["_req"] = req
                        a.spec["_node"] = w.node_id
                self._repl_actor_locked(a)
                self.cv.notify_all()
                return
            self.running.pop(a.spec["task_id"], None)
            if msg["status"] == "ok":
                # creation task reached its terminal state: count it, or
                # the ok/error ratio under-reports actor-heavy workloads
                self._count_task_terminal("ok")
                a.state = A_ALIVE
                a.worker_id = worker_id
                a.addr = msg["addr"]
                w.state = "actor"
                w.actor_id = a.actor_id
                w.current_task = None
                if a.spec.get("hold_resources", True):
                    # explicit num_cpus/num_tpus/resources are held for
                    # the actor's lifetime (released in _actor_worker_died)
                    pass
                else:
                    # reference default-actor semantics: 1 CPU for
                    # creation scheduling, 0 held while alive
                    self._release_task_resources(a.spec)
                self._repl_actor_locked(a)
            else:
                spec = w.current_task
                w.current_task = None
                if spec is None:
                    # raylet-dispatched creation: the GCS never tracked a
                    # current_task — the creation claim lives on the
                    # actor spec (same dict the lease granted)
                    spec = a.spec
                if spec is not None:
                    self._release_task_resources(spec)
                w.state = "idle"
                node = self.nodes.get(w.node_id)
                if node is not None and node.raylet_conn is None:
                    # raylet workers never enter the head's idle pool —
                    # the raylet owns their local scheduling
                    node.idle_workers.append(worker_id)
                a.state = A_DEAD
                a.death_reason = "creation failed"
                a.spec["_creation_error"] = msg.get("error")
                if a.name:
                    self.named_actors.pop((a.namespace, a.name), None)
                    self._repl_record("named", a.namespace, a.name, None)
                self._repl_actor_locked(a)
            self.cv.notify_all()
        self._pump()

    # ---------------------------------------------------------------- dispatch
    def _dispatch(self, kind: str, msg: dict) -> Optional[dict]:
        handler = getattr(self, f"_h_{kind}", None)
        if handler is None:
            raise exc.RaySystemError(f"unknown rpc kind: {kind}")
        return handler(msg)

    def local_call(self, kind: str, msg: dict) -> dict:
        """In-process RPC: dispatch directly on the caller's thread.

        Used by a driver whose head lives in its own process
        (``_INPROC_SERVER``): no socket, no serve-thread wakeup, no frame
        codec — the dominant costs of the serial round-trip on small
        hosts.  Handler exceptions propagate to the caller directly
        (the socket path's dumps_call/loads_call round-trip preserves
        type anyway); no dedup ids are needed because there is no channel
        to break mid-reply."""
        if self._shutdown:
            raise ConnectionError("GCS is shut down")
        if self._fenced and kind not in _FENCED_OK_KINDS:
            # same contract as the socket path's conn drop: the caller's
            # reconnect machinery re-dials and reaches the promoted head
            raise ConnectionError(
                "GCS fenced: a newer ledger epoch was claimed by a "
                "promoted standby")
        resp = self._dispatch(kind, msg)
        return {"error": None, **(resp or {})}

    # --- registration
    def _h_register_client(self, msg: dict) -> dict:
        with self.cv:
            wid = msg["client_id"]
            # a re-registering client (transient conn break, reattach) is
            # alive again: its ledger must accept pins (worker death
            # closed it against late stragglers)
            self._closed_ledgers.pop(wid, None)
            node_id = msg.get("node_id") or self.head_node_id
            if node_id not in self.nodes:
                # stale node id from before a head restart: adopt onto
                # this head's node (GCS fault tolerance reconnects)
                node_id = self.head_node_id
            role = msg["role"]
            existing = self.workers.get(wid)
            if existing is not None:  # extra thread-local channel re-registering
                return {"node_id": existing.node_id,
                        "head_node_id": self.head_node_id,
                        "epoch": self.epoch,
                        "store_capacity": self.store.capacity}
            if role == "worker":
                # find the placeholder created at spawn time by pid, else create
                w = None
                for cand in self.workers.values():
                    # node_id must match too: a remote-agent worker can
                    # collide on pid with a local placeholder (separate
                    # pid namespaces across hosts)
                    if cand.proc is not None and cand.proc.pid == msg["pid"] \
                            and cand.state == "starting" \
                            and cand.node_id == node_id:
                        w = cand
                        break
                if w is None:
                    w = WorkerState(wid, node_id, msg["pid"])
                    self.workers[wid] = w
                else:
                    # rekey to the worker's self-chosen id
                    del self.workers[w.worker_id]
                    w.worker_id = wid
                    self.workers[wid] = w
                node = self.nodes.get(w.node_id)
                if node is not None:
                    node.workers.add(wid)
            else:  # driver
                w = WorkerState(wid, node_id, msg["pid"])
                w.state = "driver"
                self.workers[wid] = w
                self.driver_ids.add(wid)
                self._repl_record("driver", wid)
            self.cv.notify_all()
            return {"node_id": w.node_id, "head_node_id": self.head_node_id,
                    "epoch": self.epoch,
                    "store_capacity": self.store.capacity}

    # --- objects
    def _h_put_object(self, msg: dict) -> dict:
        t0 = time.monotonic()
        with self.cv:
            self._apply_put_locked(msg["client_id"], msg)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "put_object"})
        if self.pending_tasks:
            self._pump()  # a dep-parked task may have been promoted
        return {}

    def _h_peek_meta(self, msg: dict) -> dict:
        """Non-blocking state snapshot (actor-channel reconnect dedup:
        'did this call's returns already seal?').  Sealed objects answer
        lock-free; only unsealed ones fall back to the global lock."""
        out = {}
        misses = []
        sealed = self._sealed
        for oid in msg["object_ids"]:
            e = sealed.get(oid)
            if e is not None:
                out[oid] = {"state": e["state"]}
            else:
                misses.append(oid)
        if misses:
            with self.lock:
                for oid in misses:
                    m = self.objects.get(oid)
                    out[oid] = None if m is None else {"state": m.state}
        return {"metas": out}

    def _notify_object_waiters(self, oid: str) -> None:
        """An object reached a terminal state — wake the exact get/wait
        RPCs blocked on it.  Takes only ``_waiter_lock`` (callers hold the
        global lock; readers never do)."""
        with self._waiter_lock:
            lst = self._object_waiters.pop(oid, None)
            if not lst:
                return
            for waiter in lst:
                if oid in waiter["left"]:
                    waiter["left"].discard(oid)
                    waiter["done"] = waiter.get("done", 0) + 1
                    need = waiter.get("need")
                    if (need is None and not waiter["left"]) or \
                            (need is not None and waiter["done"] >= need):
                        waiter["ev"].set()

    def _register_waiter(self, waiter: dict, oids) -> None:
        """Park ``waiter`` on each oid, then self-service any that sealed
        in the registration gap: seals publish to ``_sealed`` BEFORE
        notifying, so an entry present after registration means the
        notify may already have run without us."""
        with self._waiter_lock:
            for oid in oids:
                waiter["left"].add(oid)
                self._object_waiters.setdefault(oid, []).append(waiter)
        sealed = self._sealed
        hit = [oid for oid in oids if oid in sealed]
        if hit:
            with self._waiter_lock:
                for oid in hit:
                    self._waiter_discard_locked(waiter, oid)

    def _waiter_discard_locked(self, waiter: dict, oid: str) -> None:
        """_waiter_lock held: one oid went terminal and this thread saw it
        directly (no notify) — mirror _notify_object_waiters for it."""
        if oid not in waiter["left"]:
            return
        waiter["left"].discard(oid)
        waiter["done"] = waiter.get("done", 0) + 1
        need = waiter.get("need")
        if (need is None and not waiter["left"]) or \
                (need is not None and waiter["done"] >= need):
            waiter["ev"].set()
        lst = self._object_waiters.get(oid)
        if lst is not None:
            try:
                lst.remove(waiter)
            except ValueError:
                pass
            if not lst:
                del self._object_waiters[oid]

    def _unregister_waiter(self, waiter: dict) -> None:
        """Drop a waiter's remaining registry entries (takes _waiter_lock)."""
        with self._waiter_lock:
            for oid in list(waiter["left"]):
                lst = self._object_waiters.get(oid)
                if lst is not None:
                    try:
                        lst.remove(waiter)
                    except ValueError:
                        pass
                    if not lst:
                        del self._object_waiters[oid]

    def _scan_pending(self, oids, verify_fs: bool) -> List[str]:
        """Lock held: returns the oids still PENDING.  With ``verify_fs``,
        READY objects are checked against the filesystem (the truth, not
        our bookkeeping — a segment can vanish under us) and lost ones are
        routed to reconstruction.  Pending objects whose owner died with
        no lineage are sealed with OwnerDiedError here."""
        missing_lost = []
        pending = []
        for oid in oids:
            meta = self.objects.get(oid)
            if meta is None or meta.state == PENDING:
                pending.append(oid)
            elif verify_fs and meta.state == READY and \
                    meta.loc in ("shm", "spilled"):
                self.store.restore(oid)
                if not ShmObjectStore.exists_in_shm(oid):
                    missing_lost.append((oid, meta))
            elif verify_fs and meta.state == READY and meta.loc == "slab":
                if self.slab is None or not self.slab.exists(oid):
                    missing_lost.append((oid, meta))
        for oid, meta in missing_lost:
            # purge stale store bookkeeping first: the segment is gone,
            # but _sealed/_used may still account for it, which would
            # corrupt capacity tracking and crash later evictions
            self.store.delete_object(oid)
            self._mark_object_lost(oid, meta)
            if meta.state == PENDING:
                pending.append(oid)
        if missing_lost:
            self._pump_locked()
        for oid in pending:
            if oid[:16] in self.dead_clients:
                meta = self._get_or_create_meta(oid)
                if meta.state == PENDING and not (
                        meta.lineage_task and meta.lineage_task in self.lineage):
                    self._mark_object_lost(oid, meta)
        return [oid for oid in pending
                if (m := self.objects.get(oid)) is None or m.state == PENDING]

    def _read_sealed_fast(self, oids) -> Optional[dict]:
        """Lock-free read of terminal object metas from ``_sealed``.
        Returns the reply dict, or None when any oid is missing from the
        table (pending / remote / deleted) or fails the data-plane
        presence check (lost segment → the slow path routes it to
        reconstruction).  Never touches the global lock; the store and
        slab are their own lock domains."""
        sealed = self._sealed
        out = {}
        for oid in oids:
            e = sealed.get(oid)
            if e is None or e["loc"] == "remote":
                # remote marker: terminal for peek/wait/waiter purposes,
                # but the reply needs an addr lookup — slow path
                return None
            out[oid] = e
        for oid, e in out.items():
            loc = e["loc"]
            if loc in ("shm", "spilled"):
                self.store.restore(oid)
                if not ShmObjectStore.exists_in_shm(oid):
                    return None
                self.store.touch(oid)
            elif loc == "slab":
                if self.slab is None or not self.slab.exists(oid):
                    return None
        return out

    def _h_get_meta(self, msg: dict) -> dict:
        oids = msg["object_ids"]
        t0 = time.monotonic()
        # Hot path: every oid already sealed — reply without the global
        # lock (the common case for task args and post-completion gets).
        fast = self._read_sealed_fast(oids)
        if fast is not None:
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                    time.monotonic() - t0, tags={"kind": "get_meta_fast"})
            return {"metas": fast}
        deadline = None if msg.get("timeout") is None \
            else time.monotonic() + msg["timeout"]
        ev = threading.Event()
        waiter = {"left": set(), "ev": ev, "need": None}
        with self.cv:
            pending = self._scan_pending(oids, verify_fs=True)
        if GLOBAL_CONFIG.metrics_enabled:
            # outside the lock: metric updates must not lengthen the
            # global critical section (same rule as the other handlers)
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "get_meta_scan"})
        if pending and msg.get("nonblock"):
            # fast-path probe (see Worker._blocking_get_meta): the
            # caller avoids the task_blocked CPU-release dance when
            # everything is already sealed
            return {"pending": sorted(pending)}
        if pending:
            # registration is OUTSIDE the global lock; _register_waiter's
            # sealed-table re-check closes the scan→register gap
            self._register_waiter(waiter, pending)
        try:
            while True:
                with self._waiter_lock:
                    if not waiter["left"]:
                        break
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    with self._waiter_lock:
                        left = sorted(waiter["left"])[:3]
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {left}...")
                # rtlint: blocks-ok(get_meta IS a client-blocking rpc:
                # the per-conn dispatch thread stalls only its own
                # caller; slices capped at 1s and the caller's deadline
                # bounds the loop)
                ev.wait(timeout=min(1.0, remaining)
                        if remaining is not None else 1.0)
                ev.clear()
                with self._waiter_lock:
                    left_now = list(waiter["left"])
                if not left_now:
                    break
                # periodic sweep for state changes with no seal event
                # (owner death, lost segments under reconstruction)
                with self.cv:
                    self._scan_pending(left_now, verify_fs=False)
                    terminal = [o for o in left_now
                                if (m := self.objects.get(o)) is not None
                                and m.state != PENDING]
                if terminal:
                    with self._waiter_lock:
                        for oid in terminal:
                            self._waiter_discard_locked(waiter, oid)
        finally:
            self._unregister_waiter(waiter)
        fast = self._read_sealed_fast(oids)
        if fast is not None:
            return {"metas": fast}
        with self.cv:
            out = {}
            for oid in oids:
                meta = self.objects[oid]
                self.store.touch(oid)
                entry = {"state": meta.state, "loc": meta.loc,
                         "data": meta.data, "size": meta.size}
                if meta.loc == "remote":
                    node = self.nodes.get(meta.node_id)
                    entry["node_id"] = meta.node_id
                    entry["addr"] = node.data_addr if node else None
                out[oid] = entry
            return {"metas": out}

    def _h_wait(self, msg: dict) -> dict:
        oids = msg["object_ids"]
        num_returns = msg["num_returns"]
        # lock-free fast path: enough terminal objects in the sealed table
        sealed = self._sealed
        ready = [o for o in oids if o in sealed]
        if len(ready) >= num_returns:
            ready_set = set(ready[:num_returns])
            return {"ready": [o for o in oids if o in ready_set],
                    "not_ready": [o for o in oids if o not in ready_set]}
        deadline = None if msg.get("timeout") is None \
            else time.monotonic() + msg["timeout"]
        ev = threading.Event()
        waiter = None

        def ready_now():
            return [o for o in oids
                    if (m := self.objects.get(o)) is not None
                    and m.state != PENDING]

        with self.lock:
            ready = ready_now()
        if len(ready) < num_returns:
            pend = [o for o in oids if o not in set(ready)]
            waiter = {"left": set(), "ev": ev,
                      "need": num_returns - len(ready), "done": 0}
            # sealed-table re-check inside closes the check→register gap
            self._register_waiter(waiter, pend)
        try:
            while len(ready) < num_returns:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                # rtlint: blocks-ok(wait() IS a client-blocking rpc:
                # stalls only its own caller's per-conn thread; slices
                # capped at 0.5s and the wire-carried timeout bounds
                # the loop)
                ev.wait(timeout=min(0.5, remaining)
                        if remaining is not None else 0.5)
                ev.clear()
                with self.lock:
                    ready = ready_now()
        finally:
            if waiter is not None:
                self._unregister_waiter(waiter)
        ready_set = set(ready[:num_returns])
        return {"ready": [o for o in oids if o in ready_set],
                "not_ready": [o for o in oids if o not in ready_set]}

    def _add_refs_locked(self, ledger: str, object_ids) -> None:
        """Lock held — the ONE copy of ref-pinning (used by the add_refs
        RPC and the submit-stream 'ref' op; the two must not drift).
        Pins for a ledger release_all already tore down are dropped (the
        late-pin race; see _closed_ledgers)."""
        if ledger in self._closed_ledgers:
            return
        refs = self.client_refs[ledger]
        for oid in object_ids:
            self._get_or_create_meta(oid).refcount += 1
            refs[oid] = refs.get(oid, 0) + 1

    def _close_ledger_locked(self, ledger: str) -> None:
        self._closed_ledgers[ledger] = None
        while len(self._closed_ledgers) > 4096:
            self._closed_ledgers.popitem(last=False)

    def _apply_ref_op_locked(self, kind: str, msg: dict) -> None:
        """Lock held — apply one refcount-plane op.  The single dispatch
        point for the coalesced drain, the per-kind handlers, and the
        in-process short circuit, so semantics cannot drift."""
        if kind == "add_ref":
            self._add_refs_locked(msg.get("ledger") or msg["client_id"],
                                  (msg["object_id"],))
        elif kind == "add_refs":
            self._add_refs_locked(msg.get("ledger") or msg["client_id"],
                                  msg["object_ids"])
        elif kind == "release":
            self._apply_release_locked(msg["client_id"], msg["object_id"])
        elif kind == "release_batch":
            for oid in msg["object_ids"]:
                self._apply_release_locked(msg["client_id"], oid)
        elif kind == "release_all":
            ledger = msg["ledger"]
            self._close_ledger_locked(ledger)
            for oid, n in self.client_refs.pop(ledger, {}).items():
                self._decref(oid, n)

    def _drain_ref_ops(self, batch: List[Tuple[str, dict]]) -> None:
        """Apply a connection's coalesced refcount oneways under ONE
        global-lock acquisition, preserving their arrival order (the
        per-connection FIFO is the ordering contract pins/releases rely
        on; coalescing only ever delays application, never reorders)."""
        if not batch:
            return
        t0 = time.monotonic()
        with self.cv:
            waited = time.monotonic() - t0
            for kind, msg in batch:
                self._apply_ref_op_locked(kind, msg)
            self.cv.notify_all()
        if GLOBAL_CONFIG.metrics_enabled:
            # metric updates AFTER releasing: they take the metric's own
            # lock and must not lengthen the global critical section
            mcat.get("rtpu_gcs_lock_wait_seconds").set(
                waited, tags={"lock": "global"})
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "ref_drain"})
            mcat.get("rtpu_gcs_ref_ops_total").inc(
                len(batch), tags={"path": "coalesced"})
        batch.clear()

    def _count_inline_ref_op(self) -> None:
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_gcs_ref_ops_total").inc(tags={"path": "inline"})

    def _h_add_ref(self, msg: dict) -> dict:
        with self.cv:
            self._apply_ref_op_locked("add_ref", msg)
        self._count_inline_ref_op()
        return {}

    def _h_add_refs(self, msg: dict) -> dict:
        with self.cv:
            self._apply_ref_op_locked("add_refs", msg)
        self._count_inline_ref_op()
        return {}

    def _h_release_batch(self, msg: dict) -> dict:
        """Batched ObjectRef drops (one lock acquisition + one message for
        up to 64 decrefs — the submit hot loop's GC traffic)."""
        with self.cv:
            self._apply_ref_op_locked("release_batch", msg)
        self._count_inline_ref_op()
        return {}

    def _h_release_all(self, msg: dict) -> dict:
        """Release every ref under a transient ledger (in-flight actor args)."""
        with self.cv:
            self._apply_ref_op_locked("release_all", msg)
            self.cv.notify_all()
        self._count_inline_ref_op()
        return {}

    def _h_seal_errors(self, msg: dict) -> dict:
        with self.cv:
            for oid in msg["object_ids"]:
                meta = self._get_or_create_meta(oid)
                if meta.state == PENDING:
                    self._seal_error(oid, msg["error"])
        if self.pending_tasks:
            self._pump()
        return {}

    def _h_release(self, msg: dict) -> dict:
        return self._h_release_batch(
            {"client_id": msg["client_id"],
             "object_ids": (msg["object_id"],)})

    def _h_free_objects(self, msg: dict) -> dict:
        with self.cv:
            for oid in msg["object_ids"]:
                self._sealed.pop(oid, None)
                meta = self.objects.pop(oid, None)
                if meta is not None and meta.loc in ("shm", "spilled"):
                    self.store.delete_object(oid)
                    self._repl_record("shm", oid, None)
                elif meta is not None and meta.loc == "slab" \
                        and self.slab is not None:
                    self.slab.delete(oid)
            self.cv.notify_all()
        return {}

    # --- tasks
    def _register_spec_locked(self, spec: dict) -> None:
        """Lock held.  Pin returns + deps/borrows and enqueue the spec —
        the ONE copy of submit registration (unbatched handler and the
        batched op stream both call here; refcount rules must not drift
        between them)."""
        refs = self.client_refs[spec["owner"]]
        for oid in spec["return_ids"]:
            meta = self._get_or_create_meta(oid)
            meta.refcount += 1
            meta.has_producer = True
            refs[oid] = refs.get(oid, 0) + 1
        # pin args (top-level refs) and borrows (refs nested in values)
        # until the task reaches a terminal state
        for dep in list(spec.get("deps", ())) + list(spec.get("borrows", ())):
            meta = self._get_or_create_meta(dep)
            meta.refcount += 1
        self._push_pending(spec)

    def _apply_put_locked(self, client_id, msg: dict) -> None:
        """Lock held.  The ONE copy of object-publication bookkeeping."""
        oid = msg["object_id"]
        meta = self._get_or_create_meta(oid)
        if not msg.get("transient"):
            meta.refcount += 1  # the putting client's reference
            self.client_refs[client_id][oid] = \
                self.client_refs[client_id].get(oid, 0) + 1
        # transient: a task-arg payload — no client ref at all; the
        # submit's dep pin (same batch or rc-0-at-seal grace) owns it
        if msg["loc"] == "shm":
            self.store.adopt(oid, msg.get("size", 0))
        self._seal_object(oid, msg["loc"], msg.get("data"),
                          msg.get("size", 0), msg.get("node_id"),
                          msg.get("contained", []))

    def _apply_release_locked(self, client_id, oid: str) -> None:
        """Lock held.  The ONE copy of a single client-ref release."""
        refs = self.client_refs.get(client_id, {})
        if refs.get(oid, 0) > 0:
            refs[oid] -= 1
            if refs[oid] == 0:
                del refs[oid]
            self._decref(oid)

    def _h_submit_task(self, msg: dict) -> dict:
        spec = msg["spec"]
        try:
            with self.cv:
                self._register_spec_locked(spec)
        except Exception as e:  # noqa: BLE001 - submit is one-way: a lost
            # error would strand the caller's get() forever; seal the
            # returns with it instead
            with self.cv:
                self._fail_task(spec, e)
            raise
        # _pump_locked's capacity pre-check makes a no-capacity pump O(1);
        # no submit-site heuristic needed.
        if self.pending_tasks:
            self._pump()
        return {}

    def _h_submit_batch(self, msg: dict) -> dict:
        """Batched pipelined submission (r3): an ORDERED op stream — up to
        64 ("put", putmsg) / ("spec", spec) / ("rel", oid) entries in ONE
        message and ONE pump.  In-order application gives the same FIFO
        the unbatched path had: an arg-payload put lands before the spec
        that deps on it; a transient release lands after the spec whose
        dep pin replaces it."""
        client_id = msg.get("client_id")
        t0 = time.monotonic()
        with self.cv:
            lock_waited = time.monotonic() - t0
            for kind, payload in msg["ops"]:
                if kind == "spec":
                    try:
                        self._register_spec_locked(payload)
                    except Exception as e:  # noqa: BLE001 - see
                        # _h_submit_task: a lost error strands the getter
                        self._fail_task(payload, e)
                elif kind == "put":
                    try:
                        self._apply_put_locked(client_id, payload)
                    except Exception as e:  # noqa: BLE001 - one bad op
                        # must not discard the rest of the ordered stream,
                        # and a silently-lost put error would strand every
                        # getter (put is one-way; the ref already exists):
                        # seal the object WITH the error so parked specs
                        # and direct get()s wake with it
                        logger.exception("submit_batch: put %s failed",
                                         payload.get("object_id"))
                        oid = payload.get("object_id")
                        if oid:
                            from ray_tpu._private.serialization import \
                                serialize_to_bytes
                            self._seal_error(oid, serialize_to_bytes(e)[0])
                elif kind == "rel":
                    try:
                        self._apply_release_locked(client_id, payload)
                    except Exception:  # noqa: BLE001
                        logger.exception("submit_batch: release %s failed",
                                         payload)
                elif kind == "ref":
                    # batched add_refs riding the ordered stream (actor-
                    # call return pins — saves a per-call oneway on the
                    # direct-call hot path); MUST precede any later "rel"
                    # of the same oid, which stream order gives
                    try:
                        self._add_refs_locked(
                            payload.get("ledger") or client_id,
                            payload["object_ids"])
                    except Exception:  # noqa: BLE001
                        logger.exception("submit_batch: ref op failed")
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_gcs_lock_wait_seconds").set(
                lock_waited, tags={"lock": "global"})
            mcat.get("rtpu_gcs_hot_handler_seconds").observe(
                time.monotonic() - t0, tags={"kind": "submit_batch"})
        if self.pending_tasks:
            self._pump()
        return {}

    def _iter_queued_specs(self):
        """Lock held: every not-yet-dispatched spec — the scan queue plus
        dep-parked specs (each parked spec yielded once)."""
        yield from self.pending_tasks
        for w in self.workers.values():
            yield from w.pipeline
        seen = set()
        for specs in self.dep_waiting.values():
            for spec in specs:
                sid = id(spec)
                if sid not in seen:
                    seen.add(sid)
                    yield spec

    def _h_find_task_of_object(self, msg: dict) -> dict:
        oid = msg["object_id"]
        with self.lock:
            for spec in self._iter_queued_specs():
                if oid in spec["return_ids"]:
                    return {"task_id": spec["task_id"]}
            for wid, spec in self.running.values():
                if oid in spec["return_ids"]:
                    return {"task_id": spec["task_id"]}
            meta = self.objects.get(oid)
            if meta is not None and meta.lineage_task:
                return {"task_id": meta.lineage_task}
        raise ValueError(f"no task found for object {oid}")

    def _h_cancel_task(self, msg: dict) -> dict:
        tid = msg["task_id"]
        with self.cv:
            for spec in self._iter_queued_specs():
                if spec["task_id"] == tid:
                    spec["cancelled"] = True
                    self._fail_task(spec, exc.TaskCancelledError(tid))
                    if spec.get("_prepushed"):
                        # a worker already holds a copy of this spec
                        # (prepushed pipeline): revoke that COPY (skip-
                        # once) — a plain cancel would only target the
                        # running task, and a sticky flag would break a
                        # later legitimate re-dispatch
                        for w in self.workers.values():
                            if spec in w.pipeline:
                                w.push_ctl({"kind": "drop_queued",
                                        "pairs": [(tid,
                                                   spec.get("_dseq"))]})
                                break
                    self.cv.notify_all()
                    return {"cancelled": "pending"}
            entry = self.running.get(tid)
            if entry is not None:
                wid, spec = entry
                if wid.startswith("raylet:"):
                    # leased to a raylet: revoke there.  A queued lease
                    # never started — settle it here and now; a running
                    # one gets the in-worker cancel via the raylet.
                    node = None
                    for n in self.nodes.values():
                        if tid in n.leases_out:
                            node = n
                            break
                    spec["cancelled"] = True
                    if node is not None and spec.get("_lease_q"):
                        node.leases_out.pop(tid, None)
                        self.running.pop(tid, None)
                        self._fail_task(spec, exc.TaskCancelledError(tid))
                        node.push_raylet({"kind": "lease_revoke",
                                          "rid": None, "task_ids": [tid]})
                        self.cv.notify_all()
                        return {"cancelled": "pending"}
                    if node is not None:
                        node.push_raylet({"kind": "lease_revoke",
                                          "rid": None, "task_ids": [tid]})
                    return {"cancelled": "signalled"}
                w = self.workers.get(wid)
                if msg.get("force"):
                    if w is not None and w.proc is not None:
                        w.proc.kill()
                    return {"cancelled": "killed"}
                if w is not None:
                    w.push_ctl({"kind": "cancel", "task_id": tid})
                return {"cancelled": "signalled"}
        return {"cancelled": "not_found"}

    # --- actors
    def _h_create_actor(self, msg: dict) -> dict:
        spec = msg["spec"]
        a = ActorState(spec)
        with self.cv:
            if a.name:
                key = (a.namespace, a.name)
                if key in self.named_actors:
                    existing = self.actors.get(self.named_actors[key])
                    if existing is not None and existing.state != A_DEAD:
                        if spec.get("get_if_exists"):
                            return {"actor_id": existing.actor_id, "existing": True}
                        raise ValueError(
                            f"actor name {a.name!r} already taken in "
                            f"namespace {a.namespace!r}")
                self.named_actors[key] = a.actor_id
            self.actors[a.actor_id] = a
            self._push_pending(spec)
            if a.name:
                self._repl_record("named", a.namespace, a.name,
                                  a.actor_id)
            self._repl_actor_locked(a)
        self._persist_durable()
        self._pump()
        return {"actor_id": a.actor_id, "existing": False}

    def _h_get_actor_info(self, msg: dict) -> dict:
        deadline = None if msg.get("timeout") is None \
            else time.monotonic() + msg["timeout"]
        with self.cv:
            while True:
                a = self.actors.get(msg["actor_id"])
                if a is None:
                    raise ValueError(f"unknown actor {msg['actor_id']}")
                if a.state == A_ALIVE:
                    return {"state": a.state, "addr": a.addr,
                            "incarnation": a.incarnation}
                if a.state == A_DEAD:
                    return {"state": a.state, "addr": None,
                            "death_reason": a.death_reason,
                            "creation_error": a.spec.get("_creation_error"),
                            "incarnation": a.incarnation}
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return {"state": a.state, "addr": None,
                            "incarnation": a.incarnation}
                self.cv.wait(timeout=min(0.5, remaining) if remaining else 0.5)

    def _h_get_actor_by_name(self, msg: dict) -> dict:
        with self.cv:
            aid = self.named_actors.get((msg.get("namespace", "default"), msg["name"]))
            if aid is None:
                raise ValueError(f"no actor named {msg['name']!r}")
            a = self.actors[aid]
            return {"actor_id": aid, "class_blob_id": a.spec.get("class_blob_id"),
                    "method_meta": a.spec.get("method_meta")}

    def _h_kill_actor(self, msg: dict) -> dict:
        with self.cv:
            a = self.actors.get(msg["actor_id"])
            if a is None:
                return {}
            if msg.get("no_restart", True):
                a.spec["_killed"] = True
                a.restarts_left = 0
            a.death_reason = "ray_tpu.kill"
            self._repl_actor_locked(a)  # restart budget zeroed
            w = self.workers.get(a.worker_id) if a.worker_id else None
        if w is not None and w.proc is not None:
            try:
                w.proc.kill()
            except OSError:
                pass
        elif w is not None:
            self._push_worker_ctl(w, {"kind": "stop_worker"})
        with self.cv:
            if a.state in (A_PENDING, A_RESTARTING) and msg.get("no_restart", True):
                # not yet running anywhere: cancel the pending creation
                for spec in self._iter_queued_specs():
                    if spec.get("actor_id") == a.actor_id:
                        spec["cancelled"] = True
                a.state = A_DEAD
                if a.name:
                    self.named_actors.pop((a.namespace, a.name), None)
                    self._repl_record("named", a.namespace, a.name, None)
                self._repl_actor_locked(a)
            self.cv.notify_all()
        self._persist_durable()
        return {}

    # --- functions / kv
    def _h_export_function(self, msg: dict) -> dict:
        with self.lock:
            new = msg["fn_id"] not in self.functions
            self.functions.setdefault(msg["fn_id"], msg["blob"])
            if new:
                self._repl_record("fn", msg["fn_id"], msg["blob"])
        if new:
            self._persist_durable()
        return {}

    def _h_fetch_function(self, msg: dict) -> dict:
        deadline = time.monotonic() + 30
        with self.cv:
            while msg["fn_id"] not in self.functions:
                if time.monotonic() > deadline:
                    raise exc.RaySystemError(f"function {msg['fn_id']} not exported")
                self.cv.wait(timeout=0.5)
            return {"blob": self.functions[msg["fn_id"]]}

    def _h_kv_put(self, msg: dict) -> dict:
        metrics_key = is_metrics_key(msg["key"])
        profile_key = is_profile_key(msg["key"])
        if metrics_key and \
                (msg.get("namespace", "default") != "default"
                 or msg["key"] != f"__metrics__/{msg.get('client_id')}"):
            # reserved prefix IN EVERY NAMESPACE: metrics snapshots are
            # non-durable (the persistence filter is namespace-blind) and
            # swept ~2min after their publisher dies — silently vacuuming
            # a USER's key that happened to collide would be data loss.
            # Each process may only write its own snapshot key, and only
            # in the default namespace the publisher/sweep operate on.
            raise ValueError(
                "the '__metrics__/' KV prefix is reserved for metric "
                "snapshot publishing (ephemeral, auto-reaped); store "
                "application data under a different key")
        if profile_key and \
                (msg.get("namespace", "default") != "default"
                 or msg["key"] != f"__profile__/{msg.get('client_id')}"):
            # same reservation contract as __metrics__/ above
            raise ValueError(
                "the '__profile__/' KV prefix is reserved for profiler "
                "delta publishing (ephemeral, auto-reaped); store "
                "application data under a different key")
        telemetry_key = metrics_key or profile_key
        with self._kv_lock:
            ns = self.kv[msg.get("namespace", "default")]
            existed = msg["key"] in ns
            if not (msg.get("overwrite", True) is False and existed):
                ns[msg["key"]] = msg["value"]
                if not telemetry_key:
                    # WAL capture inside the critical section so two
                    # racing puts of one key record in table order
                    # (O(1) buffer append; telemetry keys are ephemeral
                    # and excluded from the durable set)
                    self._repl_record("kv",
                                      msg.get("namespace", "default"),
                                      msg["key"], msg["value"])
            if metrics_key:
                # receipt index shares _kv_lock with the sweep (rtlint
                # unguarded: a bare-dict update raced the sweep's
                # iterate+pop)
                self._metrics_key_seen[msg["key"]] = time.monotonic()
            elif profile_key:
                self._profile_key_seen[msg["key"]] = time.monotonic()
        if metrics_key and self._tsdb is not None:
            # history ingest rides the receipt the KV plane already has
            # (zero new RPCs) — OUTSIDE _kv_lock (json parse + ring
            # writes belong under the TSDB's own leaf lock, not a
            # no-block KV critical section); never fails the put
            try:
                self._tsdb.ingest(msg["key"].split("/", 1)[1],
                                  msg["value"])
            except Exception:  # noqa: BLE001 - telemetry best-effort
                logger.exception("tsdb ingest failed")
        if profile_key and self._profile_store is not None:
            # same receipt-riding ingest, into the profile window rings
            # — OUTSIDE _kv_lock (parse + merge under the store's own
            # leaf), and never fails the put
            try:
                self._profile_store.ingest(msg["key"].split("/", 1)[1],
                                           msg["value"])
            except Exception:  # noqa: BLE001 - telemetry best-effort
                logger.exception("profile ingest failed")
        if not telemetry_key:
            # telemetry snapshots are ephemeral by design (re-published
            # every period, reaped when the publisher dies) — every
            # process's publisher dirtying the durable snapshot each
            # cycle would turn steady-state idle into constant disk churn
            self._persist_durable()
        return {"existed": existed}

    def _h_kv_get(self, msg: dict) -> dict:
        with self._kv_lock:
            return {"value": self.kv[msg.get("namespace", "default")].get(msg["key"])}

    def _h_kv_del(self, msg: dict) -> dict:
        metrics_key = is_metrics_key(msg["key"])
        profile_key = is_profile_key(msg["key"])
        with self._kv_lock:
            existed = self.kv[msg.get("namespace", "default")].pop(msg["key"], None)
            if existed is not None and metrics_key:
                self._metrics_key_seen.pop(msg["key"], None)
            elif existed is not None and profile_key:
                self._profile_key_seen.pop(msg["key"], None)
            elif existed is not None:
                self._repl_record("kv", msg.get("namespace", "default"),
                                  msg["key"], None)
        if existed is not None and not (metrics_key or profile_key):
            # same ephemeral-telemetry exemption as _h_kv_put: metrics
            # keys are excluded from the snapshot, so reaping one must
            # not rewrite the durable state for nothing
            self._persist_durable()
        return {"deleted": existed is not None}

    def _h_kv_mget(self, msg: dict) -> dict:
        """Batched prefix read: every (key, value) under a prefix in ONE
        round trip.  The metrics collector scrapes N publishers'
        snapshots per /metrics hit — N serial kv_get RPCs would make
        scrape latency and head load linear in fleet size."""
        pref = msg["prefix"]
        with self._kv_lock:
            ns = self.kv[msg.get("namespace", "default")]
            return {"entries": {k: v for k, v in ns.items()
                                if isinstance(k, type(pref))
                                and k.startswith(pref)}}

    def _h_kv_keys(self, msg: dict) -> dict:
        with self._kv_lock:
            ns = self.kv[msg.get("namespace", "default")]
            prefix = msg.get("prefix", b"")
            return {"keys": [k for k in ns if k.startswith(prefix)]}

    # --- placement groups
    def _h_pg_create(self, msg: dict) -> dict:
        from ray_tpu._private.pg_scheduler import schedule_bundles
        pg = PgState(msg["pg_id"], msg["bundles"], msg["strategy"], msg.get("name", ""))
        with self.cv:
            assignment = schedule_bundles(
                [n for n in self.nodes.values() if n.schedulable()],
                pg.bundles, pg.strategy)
            if assignment is not None:
                for i, node_id in enumerate(assignment):
                    self.nodes[node_id].acquire(pg.bundles[i])
                    pg.assignment[i] = node_id
                pg.state = READY
            self.pgs[pg.pg_id] = pg
            self._repl_record("pg", pg.pg_id,
                              {"bundles": pg.bundles,
                               "strategy": pg.strategy, "name": pg.name})
            self.cv.notify_all()
        self._persist_durable()
        return {"state": pg.state}

    def _h_pg_wait(self, msg: dict) -> dict:
        from ray_tpu._private.pg_scheduler import schedule_bundles
        deadline = None if msg.get("timeout") is None \
            else time.monotonic() + msg["timeout"]
        with self.cv:
            while True:
                pg = self.pgs.get(msg["pg_id"])
                if pg is None:
                    raise ValueError("placement group removed")
                if pg.state == READY:
                    return {"ready": True, "assignment": pg.assignment}
                # retry scheduling (nodes may have joined)
                assignment = schedule_bundles(
                    [n for n in self.nodes.values() if n.schedulable()],
                    pg.bundles, pg.strategy)
                if assignment is not None:
                    for i, node_id in enumerate(assignment):
                        self.nodes[node_id].acquire(pg.bundles[i])
                        pg.assignment[i] = node_id
                    pg.state = READY
                    self.cv.notify_all()
                    continue
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return {"ready": False, "assignment": None}
                self.cv.wait(timeout=min(0.5, remaining) if remaining else 0.5)

    def _h_pg_remove(self, msg: dict) -> dict:
        with self.cv:
            pg = self.pgs.pop(msg["pg_id"], None)
            if pg is not None and pg.state == READY:
                for i, node_id in enumerate(pg.assignment):
                    node = self.nodes.get(node_id)
                    if node is not None:
                        node.release_res(pg.bundles[i])
            if pg is not None:
                self._repl_record("pg", msg["pg_id"], None)
            self.cv.notify_all()
        self._persist_durable()
        self._pump()
        return {}

    def _h_pg_table(self, msg: dict) -> dict:
        with self.lock:
            return {"pgs": {pid: {"state": pg.state, "strategy": pg.strategy,
                                  "bundles": pg.bundles,
                                  "assignment": pg.assignment}
                            for pid, pg in self.pgs.items()}}

    # --- cluster / state API
    def _h_add_node(self, msg: dict) -> dict:
        nid = self.add_node_internal(NodeID.new(), msg["resources"],
                                     labels=msg.get("labels"),
                                     remote=bool(msg.get("remote")),
                                     data_addr=msg.get("data_addr"),
                                     data_proto=int(msg.get("data_proto")
                                                    or 0))
        self._pump()
        # session name: same-host raylets drop their flight-recorder
        # rings into this session's tmpfs dir so `debug dump` sees them
        return {"node_id": nid, "session": self.session.path.name}

    def _h_raylet_table(self, msg: dict) -> dict:
        """Per-node local-scheduler state for `ray_tpu status` and
        `debug dump`: held leases, local queue depth, reconcile age."""
        with self.lock:
            rows = []
            for n in self.nodes.values():
                if n.raylet_conn is None and not n.raylet_stats:
                    continue
                rows.append({
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "attached": n.raylet_conn is not None,
                    "held_leases": len(n.leases_out),
                    "queued_leases": n.queued_lease_count(),
                    "last_reconcile_age_s": round(
                        n.raylet_reconcile_age, 3),
                    "stats": dict(n.raylet_stats),
                })
            return {"raylets": rows}

    def _h_remove_node(self, msg: dict) -> dict:
        self.remove_node_internal(msg["node_id"])
        return {}

    # ------------------------------------------------- fleet elasticity (§4j)
    def _h_node_draining(self, msg: dict) -> dict:
        """Provider-initiated preemption warning: mark the node draining
        so placement avoids it, and publish a fleet event the elasticity
        manager / train backend subscribers react to.  The node is
        addressed by id, or by a label match (``label={"ray-pod": name}``
        — the Kubernetes provider only knows pod names)."""
        deadline_s = float(msg.get("deadline_s") or 0.0)
        sel = msg.get("label") or {}
        node_id = msg.get("node_id") or ""
        if sel:
            with self.cv:
                # label fallback also covers a stale/unknown node_id —
                # the Kubernetes provider only reliably knows pod names
                if node_id not in self.nodes:
                    for n in self.nodes.values():
                        if all(n.labels.get(k) == v
                               for k, v in sel.items()):
                            node_id = n.node_id
                            break
        ok = self.drain_node_internal(
            node_id, deadline_s=deadline_s,
            reason=str(msg.get("reason") or "preemption"))
        return {"ok": ok, "node_id": node_id if ok else None}

    def drain_node_internal(self, node_id: str, deadline_s: float = 0.0,
                            reason: str = "preemption",
                            only_if_running: bool = False) -> bool:
        """Mark one node draining (placement avoids it; work already
        there keeps running) and publish the ``node_draining`` fleet
        event.  Shared by the RPC handler above and the autopilot's
        straggler reflex (§4n) — remediation drains ride the exact path
        provider warnings do, so every subscriber reacts the same way.
        ``only_if_running`` (the autopilot) refuses a node that is
        already draining: claiming a provider-drained node would let a
        later autopilot undrain cancel the provider's preemption
        warning — the autopilot only owns drains it issued."""
        with self.cv:
            node = self.nodes.get(node_id or "")
            if node is None or not node.alive:
                return False
            already = node.phase == "draining"
            if only_if_running and node.phase != "running":
                return False
            node.phase = "draining"
            node.drain_reason = reason
            if deadline_s > 0:
                node.drain_deadline = time.monotonic() + deadline_s
            self.cv.notify_all()
        if not already:
            self._fleet_event("node_draining", node.node_id,
                              reason=reason, deadline_s=deadline_s)
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_elastic_node_draining_total").inc(
                    tags={"reason": reason})
        return True

    def undrain_node_internal(self, node_id: str,
                              only_reason: Optional[str] = None) -> bool:
        """Return a drained node to the schedulable pool (the autopilot's
        recovery path: the straggler signal cleared, the host is healthy
        again).  Publishes ``node_undrained`` and re-pumps so backlogged
        work can land on the restored capacity.  ``only_reason`` (the
        autopilot passes "straggler") refuses when the CURRENT drain
        reason differs — a provider preemption warning that superseded
        the remediation drain must not be cancelled by the autopilot's
        recovery timer."""
        with self.cv:
            node = self.nodes.get(node_id or "")
            if node is None or not node.alive or node.phase != "draining":
                return False
            if only_reason is not None and node.drain_reason != only_reason:
                return False
            node.phase = "running"
            node.drain_reason = ""
            node.drain_deadline = None
            self.cv.notify_all()
        self._fleet_event("node_undrained", node_id)
        self._pump()
        return True

    def _h_metrics_query(self, msg: dict) -> dict:
        """Query the head TSDB (DESIGN.md §4k): ``op`` selects instant
        ``query`` (default), ``query_range`` (sparkline feed), ``series``
        (metadata listing), or ``stats``.  Runs entirely off the GCS
        locks — the store has its own leaf lock."""
        if self._tsdb is None:
            return {"results": [], "disabled": True}
        op = msg.get("op", "query")
        if op == "stats":
            return {"stats": self._tsdb.stats()}
        if op == "series":
            return {"series": self._tsdb.list_series(msg.get("match"))}
        if op == "query_range":
            return {"results": self._tsdb.query_range(
                msg["expr"], start=msg.get("start"), end=msg.get("end"),
                step=msg.get("step"))}
        if op == "forecast":
            return {"results": self._tsdb.forecast(
                msg["expr"], float(msg.get("horizon_s") or 0.0),
                period_s=float(msg.get("period_s") or 86400.0),
                smooth_s=float(msg.get("smooth_s") or 600.0),
                now=msg.get("at"))}
        return {"results": self._tsdb.query(msg["expr"],
                                            at=msg.get("at"))}

    def _h_profile_query(self, msg: dict) -> dict:
        """Query the head ProfileStore (DESIGN.md §4o): ``op`` selects
        window aggregate ``profile`` (default; optional proc/node
        filter), ``diff`` (recent window A vs the baseline window B
        immediately before it), or ``stats``.  Runs entirely off the
        GCS locks — the store has its own leaf lock."""
        if self._profile_store is None:
            return {"samples": 0, "stacks": {}, "procs": [],
                    "disabled": True}
        op = msg.get("op", "profile")
        if op == "stats":
            return {"stats": self._profile_store.stats()}
        if op == "diff":
            return self._profile_store.diff(
                float(msg.get("window_a") or 300.0),
                float(msg.get("window_b") or 300.0),
                proc=msg.get("proc"))
        return self._profile_store.profile(
            window_s=float(msg.get("window_s") or 300.0),
            proc=msg.get("proc"), node_id=msg.get("node_id"))

    def _run_detectors(self) -> None:
        """Monitor-loop tick: run the TSDB anomaly detectors and emit
        what they find into the fleet-event feed (§4j), the flight
        recorder (§4h), and the anomaly counter.  No GCS lock is held
        while the detectors read the store; the worker→node map is
        snapshotted under the global lock FIRST so nothing nests."""
        found: List[dict] = []
        for det in self._detectors:
            found.extend(det.check())
        if not found:
            return
        with self.lock:
            node_of = {w.worker_id: w.node_id
                       for w in self.workers.values()}
        from ray_tpu._private import flight_recorder
        for ev in found:
            kind = ev.pop("kind")
            node_id = node_of.get(ev.get("worker"))
            # post-mortem capture (§4o): bundle the offending node's
            # hot stacks + rings BEFORE anyone reacts — by the time a
            # human looks, the autopilot may already have drained it
            iid = self._capture_incident(kind, node_id, detail=ev)
            if iid is not None:
                ev = dict(ev, incident=iid)
            self._fleet_event(kind, node_id, **ev)
            if flight_recorder.enabled():
                flight_recorder.record(
                    "anomaly", f"{kind} " + " ".join(
                        f"{k}={v}" for k, v in sorted(ev.items())))
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_anomaly_events_total").inc(
                    tags={"kind": kind})
            logger.warning("anomaly detected: %s %s", kind, ev)

    def _tick_autopilot(self) -> None:
        """One autopilot reflex pass (monitor loop, §4n): hand the
        reflex engine every fleet event it has not seen (cursor over
        the same ring ``fleet_events`` serves, read head-side without
        an RPC), then tick."""
        with self._events_lock:
            events = [dict(e) for e in self._fleet_events
                      if e["seq"] > self._autopilot_cursor]
            self._autopilot_cursor = self._fleet_event_seq
        for ev in events:
            self._autopilot.observe(ev)
        self._autopilot.tick()

    def _capture_incident(self, kind: str, node_id: Optional[str],
                          detail: Optional[dict] = None) -> Optional[str]:
        """Write one bounded post-mortem bundle into
        ``<session>/incidents/<ts>_<kind>_<node8>/`` (DESIGN.md §4o):
        the offending node's recent profile window, an all-worker stack
        dump, the flight-recorder ring tails, and TSDB sparkline data
        around the event.  Monitor thread only (the detector pass and
        the autopilot's actuator callback both run there): one bundle
        per node per ``incident_dedup_s`` — a refire or the drain that
        follows reuses the existing id, so the bundle is written
        exactly once per episode.  Returns the bundle id (or None when
        the profiling plane is disabled / capture failed)."""
        if self._profile_store is None:
            return None
        now = time.monotonic()
        dedup_key = node_id or "cluster"
        prev = self._incident_recent.get(dedup_key)
        if prev is not None and \
                now - prev[0] < GLOBAL_CONFIG.incident_dedup_s:
            return prev[1]
        ts = time.time()
        iid = (time.strftime("%Y%m%d_%H%M%S", time.localtime(ts))
               + f"_{kind}_{(node_id or 'cluster')[:8]}")
        root = os.path.join(str(self.session.path), "incidents")
        inc_dir = os.path.join(root, iid)
        try:
            os.makedirs(inc_dir, exist_ok=True)
            bundle: Dict[str, dict] = {
                "meta.json": {"id": iid, "kind": kind,
                              "node_id": node_id, "ts": ts,
                              "detail": detail or {}}}
            # the node's last profile windows; cluster-wide fallback
            # when the node published nothing yet (short-lived victim)
            prof = self._profile_store.profile(window_s=600.0,
                                               node_id=node_id)
            if node_id is not None and not prof["samples"]:
                prof = self._profile_store.profile(window_s=600.0)
            bundle["profile.json"] = prof
            try:
                bundle["stacks.json"] = self._h_stack({"timeout": 2.0})
            except Exception:  # noqa: BLE001 - best-effort layer
                bundle["stacks.json"] = {"stacks": {}, "expected": 0}
            from ray_tpu._private import flight_recorder
            try:
                bundle["flight.json"] = flight_recorder.collect(
                    self.session.path, tail=200)
            except Exception:  # noqa: BLE001 - best-effort layer
                bundle["flight.json"] = {}
            spark: Dict[str, list] = {}
            if self._tsdb is not None:
                for expr in (
                        "sum(rate(rtpu_tasks_total[60s]))",
                        "quantile_over_time(0.99, "
                        "rtpu_train_step_seconds[2m])"):
                    try:
                        spark[expr] = self._tsdb.query_range(
                            expr, start=ts - 600.0, end=ts, step=10.0)
                    except Exception:  # noqa: BLE001 - sparkline only
                        spark[expr] = []
            bundle["tsdb.json"] = spark
            for name, doc in bundle.items():
                with open(os.path.join(inc_dir, name), "w") as f:
                    json.dump(doc, f, indent=2, default=str)
        except Exception:  # noqa: BLE001 - capture must not kill GCS
            logger.exception("incident capture failed (%s, %s)",
                             kind, node_id)
            shutil.rmtree(inc_dir, ignore_errors=True)
            return None
        self._incident_recent[dedup_key] = (now, iid)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_incidents_total").inc(tags={"kind": kind})
        logger.warning("incident bundle captured: %s", iid)
        # bounded disk: evict the oldest bundles past incident_max
        # (ids sort by their timestamp prefix)
        try:
            dirs = sorted(d for d in os.listdir(root)
                          if os.path.isdir(os.path.join(root, d)))
            while len(dirs) > max(1, GLOBAL_CONFIG.incident_max):
                shutil.rmtree(os.path.join(root, dirs.pop(0)),
                              ignore_errors=True)
        except OSError:
            pass
        return iid

    def _h_debug_incidents(self, msg: dict) -> dict:
        """List captured incident bundles (id + meta), or with ``id``
        fetch one bundle's files (`ray_tpu debug incidents`)."""
        root = os.path.join(str(self.session.path), "incidents")
        iid = msg.get("id")
        if iid:
            if os.sep in iid or iid.startswith("."):
                raise ValueError(f"bad incident id {iid!r}")
            d = os.path.join(root, iid)
            if not os.path.isdir(d):
                return {"error": f"no incident {iid!r}"}
            files: Dict[str, str] = {}
            for name in sorted(os.listdir(d)):
                try:
                    with open(os.path.join(d, name), "rb") as f:
                        files[name] = f.read(4 * 1024 * 1024) \
                            .decode("utf-8", "replace")
                except OSError:
                    continue
            return {"id": iid, "files": files}
        out: List[dict] = []
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                rec = {"id": name}
                try:
                    with open(os.path.join(root, name,
                                           "meta.json")) as f:
                        rec.update(json.load(f))
                except (OSError, ValueError):
                    pass
                out.append(rec)
        return {"incidents": out}

    def _h_autopilot_status(self, msg: dict) -> dict:
        """The autopilot's bounded action history + reflex counters
        (§4n) — what `ray_tpu status` and the chaos tests read to
        assert the loop acted (and, just as important, that it did NOT
        act more than its rate limits allow)."""
        if self._autopilot is None:
            return {"enabled": False, "actions": [], "stats": {}}
        return {"enabled": True,
                "actions": self._autopilot.actions(
                    int(msg.get("limit") or 50)),
                "stats": self._autopilot.stats()}

    def _h_fleet_events(self, msg: dict) -> dict:
        """Cursor read of the fleet lifecycle feed: events with
        seq > ``since`` (bounded ring — a lagging subscriber may miss
        events and should reconcile against list_nodes)."""
        since = int(msg.get("since") or 0)
        with self._events_lock:
            events = [dict(e) for e in self._fleet_events
                      if e["seq"] > since]
            seq = self._fleet_event_seq
        return {"events": events, "seq": seq}

    def _h_elastic_event(self, msg: dict) -> dict:
        """The elasticity manager reports a re-mesh (or restart) so
        `ray_tpu status` / the dashboard can show the last transition
        without reaching into the manager's process."""
        rec = {"ts": time.time(),
               "group": msg.get("group"),
               "action": msg.get("action"),       # remesh | restart
               "generation": msg.get("generation"),
               "world_size": msg.get("world_size"),
               "detail": msg.get("detail") or {}}
        with self._events_lock:
            self._last_remesh = rec
        self._fleet_event("remesh", None, **{k: v for k, v in rec.items()
                                             if k != "ts"})
        return {}

    def _h_fleet_state(self, msg: dict) -> dict:
        """One-call fleet rollup for `ray_tpu status` / state.py: nodes
        by lifecycle phase, the current demand backlog, and the last
        elastic re-mesh event (DESIGN.md §4j)."""
        demand = self._h_resource_demand({})
        now = time.monotonic()
        with self.lock:
            phases: Dict[str, int] = {}
            draining = []
            for n in self.nodes.values():
                phase = n.phase if n.alive else "terminating"
                phases[phase] = phases.get(phase, 0) + 1
                if phase == "draining":
                    draining.append({
                        "node_id": n.node_id,
                        "reason": n.drain_reason,
                        "deadline_in_s": (
                            round(n.drain_deadline - now, 3)
                            if n.drain_deadline else None)})
        with self._events_lock:
            last_remesh = dict(self._last_remesh) \
                if self._last_remesh else None
            seq = self._fleet_event_seq
        backlog = demand["task_shapes"] + demand["pg_bundles"]
        return {"phases": phases, "draining": draining,
                "demand_backlog": backlog,
                "demand_backlog_count": len(backlog),
                "last_remesh": last_remesh, "event_seq": seq}

    def _h_pick_oom_victim(self, msg: dict) -> dict:
        """A NodeAgent reports local memory pressure; the head picks the
        newest plain-task worker ON THAT NODE (policy stays central, the
        kill stays local to the pid's own namespace — reference: per-node
        MemoryMonitor inside the raylet).  The task is NOT marked here:
        the agent verifies the pid is one it owns and still alive, then
        calls confirm_oom_kill immediately before killing — a skipped kill
        (stale head view, already-exited proc) must not mislabel a later
        unrelated death as OOM."""
        from ray_tpu._private.memory_monitor import pick_oom_victim
        victim = pick_oom_victim(self, node_id=msg["node_id"])
        if victim is None:
            return {"pid": None, "worker_id": None}
        w, spec = victim
        logger.warning(
            "node %s reports memory pressure (%.0f%%): designating newest "
            "task %s (worker %s pid=%s) for OOM kill",
            msg["node_id"][:8], 100 * msg.get("frac", 0),
            spec.get("name", spec["task_id"]), w.worker_id[:8], w.pid)
        return {"pid": w.pid, "worker_id": w.worker_id,
                "task_id": spec["task_id"]}

    def _h_confirm_oom_kill(self, msg: dict) -> dict:
        """The agent is about to kill this pid: mark the worker's current
        task so its death surfaces as a retriable OutOfMemoryError.  The
        task_id must still match the pick — the picked task may have
        completed and the pooled worker started an unrelated one during
        the pick→confirm window; that task must not be doomed as OOM."""
        with self.lock:
            w = self.workers.get(msg["worker_id"])
            if w is not None and w.pid == msg["pid"] \
                    and w.current_task is not None \
                    and w.current_task.get("task_id") == msg.get("task_id"):
                w.current_task["_oom_killed"] = True
                return {"ok": True}
        return {"ok": False}

    def _h_cluster_resources(self, msg: dict) -> dict:
        with self.lock:
            total: Dict[str, float] = defaultdict(float)
            avail: Dict[str, float] = defaultdict(float)
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources_total.items():
                    total[k] += v
                for k, v in n.resources_avail.items():
                    avail[k] += v
            return {"total": dict(total), "available": dict(avail)}

    def _h_list_nodes(self, msg: dict) -> dict:
        with self.lock:
            return {"nodes": [{
                "node_id": n.node_id, "alive": n.alive,
                "phase": n.phase if n.alive else "terminating",
                "resources_total": n.resources_total,
                "resources_available": n.resources_avail,
                "num_workers": len(n.workers), "labels": n.labels,
            } for n in self.nodes.values()]}

    def _h_list_actors(self, msg: dict) -> dict:
        with self.lock:
            return {"actors": [{
                "actor_id": a.actor_id, "state": a.state, "name": a.name,
                "class_name": a.spec.get("class_name"),
                "node_id": (self.workers[a.worker_id].node_id
                            if a.worker_id in self.workers else None),
                "pid": (self.workers[a.worker_id].pid
                        if a.worker_id in self.workers else None),
            } for a in self.actors.values()]}

    def _h_list_tasks(self, msg: dict) -> dict:
        with self.lock:
            out = []
            for wid, spec in self.running.values():
                out.append({"task_id": spec["task_id"], "name": spec.get("name"),
                            "state": "RUNNING", "worker_id": wid})
            for spec in self.pending_tasks:
                out.append({"task_id": spec["task_id"], "name": spec.get("name"),
                            "state": "PENDING_SCHEDULING", "worker_id": None})
            seen = {id(sp) for sp in self.pending_tasks}
            for specs in self.dep_waiting.values():
                for spec in specs:
                    if id(spec) not in seen:
                        seen.add(id(spec))
                        out.append({"task_id": spec["task_id"],
                                    "name": spec.get("name"),
                                    "state": "PENDING_ARGS",
                                    "worker_id": None})
            return {"tasks": out}

    def _h_list_objects(self, msg: dict) -> dict:
        with self.lock:
            return {"objects": [{
                "object_id": oid, "state": m.state, "loc": m.loc,
                "size": m.size, "refcount": m.refcount,
            } for oid, m in self.objects.items()]}

    def _h_list_workers(self, msg: dict) -> dict:
        with self.lock:
            return {"workers": [{
                "worker_id": w.worker_id, "node_id": w.node_id, "pid": w.pid,
                "state": w.state, "actor_id": w.actor_id,
            } for w in self.workers.values()]}

    def _h_resource_demand(self, msg: dict) -> dict:
        """Unfulfilled resource shapes for the autoscaler: dep-ready pending
        tasks/actor creations that lack capacity, plus unplaced PG bundles
        (reference: autoscaler load_metrics fed by the GCS resource view)."""
        with self.lock:
            shapes = []
            for spec in self.pending_tasks:
                if self._deps_status(spec) == "ready":
                    shapes.append(self._task_resources(spec))
            for spec in self.infeasible_tasks:
                shapes.append(self._task_resources(spec))
            bundles = []
            for pg in self.pgs.values():
                if pg.state == PENDING:
                    for i, b in enumerate(pg.bundles):
                        if pg.assignment[i] is None:
                            bundles.append(dict(b))
            return {"task_shapes": shapes, "pg_bundles": bundles}

    def _resolve_object_bytes(self, oid: str):
        """One object-resolution ladder for the cross-host data path:
        → ("inline", bytes) | ("slab", bytes) | ("shm", Path) | None."""
        with self.lock:
            meta = self.objects.get(oid)
            if meta is None or meta.state != READY:
                return None
            loc, data = meta.loc, meta.data
        if loc == "remote":
            # head acting as the RELAY FALLBACK for a puller that cannot
            # reach the holder host (hub-spoke): pull the spool copy into
            # the local store once, then serve it like any shm object
            if not self._pull_remote_local(oid):
                return None
            with self.lock:
                meta = self.objects.get(oid)
                if meta is None or meta.state != READY:
                    return None
                loc, data = meta.loc, meta.data
        if loc == "inline":
            return ("inline", data)
        if loc == "slab":
            blob = self.slab.get(oid) if self.slab else None
            return None if blob is None else ("slab", blob)
        self.store.restore(oid)
        from ray_tpu._private.shm_store import _seg_path
        return ("shm", _seg_path(oid))

    def _pull_remote_local(self, oid: str) -> bool:
        """Pull a remote-spooled object into the head's shm store
        (concurrent pulls of the same oid coalesce — reference:
        PullManager dedup)."""
        with self.lock:
            meta = self.objects.get(oid)
            if meta is None or meta.loc != "remote":
                return meta is not None and meta.state == READY
            node = self.nodes.get(meta.node_id)
            addr = node.data_addr if node else None
            ev = self._remote_pulls.get(oid)
            leader = ev is None
            if leader:
                ev = self._remote_pulls[oid] = threading.Event()
        if not leader:
            # rtlint: blocks-ok(follower of a coalesced remote pull:
            # parks its own caller only, 120s literal cap, and the
            # leader settles or times out the shared event first)
            ev.wait(timeout=120)
            with self.lock:
                m = self.objects.get(oid)
                return m is not None and m.state == READY \
                    and m.loc != "remote"
        try:
            if addr is None:
                return False
            from ray_tpu._private.shm_store import _seg_path
            if protocol.parse_tcp_addr(addr) is None:
                return False
            with self.lock:
                m = self.objects.get(oid)
                size = m.size if m is not None else None
            wire = self._data_pool.pull(addr, oid, size=size)
            seg = _seg_path(oid)
            tmp = seg.with_name(seg.name + ".pull")
            tmp.write_bytes(wire)
            os.replace(tmp, seg)
            with self.cv:
                self.store.adopt(oid, len(wire))
                meta = self.objects.get(oid)
                if meta is not None:
                    meta.loc = "shm"
                    meta.size = len(wire)
                    meta.node_id = self.head_node_id
                    if meta.state == READY:
                        self._publish_sealed_locked(oid, READY, "shm", None,
                                                    len(wire))
            # the head owns the object now — drop the holder's spool copy
            # or relay-fallback traffic accumulates dead files on A
            threading.Thread(target=self._data_pool.delete_batch,
                             args=(addr, [oid]),
                             daemon=True, name="gcs-peer-delete-one").start()
            return True
        except (OSError, EOFError, FileNotFoundError, ConnectionError):
            return False
        finally:
            with self.lock:
                self._remote_pulls.pop(oid, None)
            ev.set()

    def _h_fetch_object(self, msg: dict) -> dict:
        """Object bytes through the control plane — the cross-host data
        path (a remote host cannot mmap this machine's /dev/shm).  Objects
        above ``transfer_chunk_bytes`` answer ``{"chunked": True, size}``;
        the caller then streams ``fetch_chunk`` requests (reference:
        ObjectManager chunked transfer, SURVEY.md §2.1) so the control
        plane never carries one monolithic multi-hundred-MB message."""
        chunk = GLOBAL_CONFIG.transfer_chunk_bytes
        got = self._resolve_object_bytes(msg["object_id"])
        if got is None:
            return {"data": None}
        loc, payload = got
        try:
            if loc == "shm":
                size = payload.stat().st_size
                if size > chunk:
                    return {"chunked": True, "size": size}
                return {"data": payload.read_bytes()}
        except (FileNotFoundError, OSError):
            return {"data": None}
        if loc != "inline" and len(payload) > chunk:
            return {"chunked": True, "size": len(payload)}
        return {"data": payload}

    def _h_put_chunk(self, msg: dict) -> dict:
        """One chunk of a large object being uploaded from a remote host
        (the inbound half of chunked transfer: remote task/actor results
        and remote ``put``s).  Chunks pwrite straight into the object's
        tmpfs segment at their offset — the daemon never holds the whole
        object in its heap (that would defeat the point of chunking).
        The uploader references the sealed segment with loc="shm"."""
        oid, off, total = msg["object_id"], msg["offset"], msg["total"]
        data = msg["data"]
        if total > self.store.capacity:
            raise ValueError(
                f"chunked upload of {total} bytes exceeds store capacity "
                f"{self.store.capacity}")
        from ray_tpu._private.shm_store import _seg_path
        with self.lock:
            st = self._staging.get(oid)
            if st is None:
                fd = os.open(str(_seg_path(oid)),
                             os.O_CREAT | os.O_RDWR, 0o600)
                try:
                    os.ftruncate(fd, max(total, 1))
                except OSError:
                    # ENOSPC on a full tmpfs: the fd must not outlive
                    # the failed reservation (one leaked fd per retried
                    # upload chunk adds up to EMFILE on a busy head)
                    os.close(fd)
                    raise
                st = {"fd": fd, "offsets": set(), "got": 0,
                      "ts": time.time()}
                self._staging[oid] = st
            os.pwrite(st["fd"], data, off)
            # Completion tracks *covered offsets*, not cumulative bytes: a
            # retried/duplicated chunk must not double-count and seal a
            # segment that still has holes.
            if off not in st["offsets"]:
                st["offsets"].add(off)
                st["got"] += len(data)
            st["ts"] = time.time()
            done = st["got"] >= total
            if done:
                os.close(st["fd"])
                self._staging.pop(oid, None)
        return {"done": done}

    def _h_fetch_chunk(self, msg: dict) -> dict:
        """One chunk of a large object (offset/length pread — stateless,
        so retries and concurrent pullers need no server-side sessions)."""
        offset, length = msg["offset"], msg["length"]
        got = self._resolve_object_bytes(msg["object_id"])
        if got is None:
            return {"data": None}
        loc, payload = got
        if loc == "shm":
            try:
                with open(payload, "rb") as f:
                    return {"data": os.pread(f.fileno(), length, offset)}
            except (FileNotFoundError, OSError):
                return {"data": None}
        return {"data": bytes(memoryview(payload)[offset:offset + length])}

    def _h_store_stats(self, msg: dict) -> dict:
        return {"stats": self.store.stats()}

    def _h_ingest_events(self, msg: dict) -> dict:
        """Timeline events from processes with no task conn (drivers):
        span traces, merged device traces (util/tracing.py)."""
        with self._events_lock:
            self.events.extend(msg["events"])
        return {}

    def _h_timeline(self, msg: dict) -> dict:
        with self._events_lock:
            return {"events": list(self.events)}

    def _h_stack(self, msg: dict) -> dict:
        """Stack dumps from every live worker (reference: ``ray stack``
        via py-spy; here an in-process all-threads snapshot).  Each call
        collects into its own request record (concurrent calls don't
        clobber each other), waits on the cv (no polling), and only
        counts workers whose dump request was actually delivered."""
        collected: Dict[str, str] = {}
        with self.cv:
            self._stack_reqs.append(collected)
            targets = [w for w in self.workers.values()
                       if (w.state in ("idle", "busy", "actor")
                           and w.task_conn is not None)
                       or (w.state in ("starting", "actor")
                           and self.nodes.get(w.node_id) is not None
                           and self.nodes[w.node_id].raylet_conn
                           is not None)]
        try:
            targets = [w for w in targets
                       if self._push_worker_ctl(w, {"kind": "dump_stack"})]
            deadline = time.time() + float(msg.get("timeout", 3.0))
            with self.cv:
                while len(collected) < len(targets):
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self.cv.wait(timeout=min(0.5, remaining))
        finally:
            with self.cv:
                try:
                    self._stack_reqs.remove(collected)
                except ValueError:
                    pass
        return {"stacks": dict(collected), "expected": len(targets)}

    def _h_debug_dump(self, msg: dict) -> dict:
        """Flight-recorder dump for every process of this session
        (`ray_tpu debug dump`).  Rings are shared-mmap files in the
        session dir, so dead (SIGKILLed) processes' recent frames read
        exactly like live ones — no cooperation needed."""
        from ray_tpu._private import flight_recorder
        return {"procs": flight_recorder.collect(
            self.session.path, tail=int(msg.get("tail", 200))),
            "raylets": self._h_raylet_table({})["raylets"]}

    def _h_ping(self, msg: dict) -> dict:
        return {"pong": True, "time": time.time()}

    # ------------------------------------------------------------------ close
    def shutdown(self) -> None:
        global _INPROC_SERVER
        if _INPROC_SERVER is self:
            _INPROC_SERVER = None
        self._shutdown = True
        if self._autopilot is not None:
            # stop the supervised standby FIRST: a clean cluster stop
            # must not leave a warm standby to promote over the corpse
            try:
                self._autopilot.actuator.shutdown()
            except Exception:  # noqa: BLE001 - child already gone
                logger.debug("autopilot shutdown failed", exc_info=True)
        with self.cv:
            # tell attached raylets to tear their nodes down cleanly
            for n in self.nodes.values():
                if n.raylet_conn is not None:
                    n.push_raylet({"kind": "raylet_stop", "rid": None})
            procs = [w.proc for w in self.workers.values() if w.proc is not None]
            # proc-less workers (reattached after a head restart) have no
            # pid here to signal — tell them to stop so they don't sit in
            # the GCS-reconnect grace loop after a CLEAN shutdown
            for w in self.workers.values():
                if w.proc is None and w.state not in ("driver", "dead"):
                    try:
                        self._push_worker_ctl(w, {"kind": "stop_worker"})
                    except Exception:  # noqa: BLE001 - already gone
                        pass
            self.cv.notify_all()
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + 2
        for p in procs:
            try:
                p.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            self._listener.close()
        except OSError:
            pass
        self._data_pool.close_all()
        if self._repl_hub is not None:
            # discharge the WAL fd and every standby conn (the runtime
            # resource oracle asserts this below)
            self._repl_hub.close()
        self.store.shutdown()
        if self.slab is not None:
            self.slab.close()
        # discharge the flight recorder's mmap (the ring FILE stays —
        # it is the crash artifact); must precede the leak assert below
        from ray_tpu._private import flight_recorder
        flight_recorder.close()
        # stop the head's sampling profiler thread (daemon, but a clean
        # shutdown joins it so no sampler races interpreter teardown)
        from ray_tpu.util import profiler as profiler_mod
        profiler_mod.close()
        # leak oracle: a CLEAN head shutdown must leave zero net
        # tracked resources (the driver's Worker.shutdown ran first —
        # __init__.shutdown() orders worker before head)
        from ray_tpu._private import resource_sanitizer
        resource_sanitizer.assert_clean_at_shutdown("gcs-shutdown")
