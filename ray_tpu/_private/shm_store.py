"""Shared-memory object store (plasma-equivalent data plane).

Reference: ``src/ray/object_manager/plasma/`` (SURVEY.md §2.1) — a per-node
shared-memory immutable object store with create→seal→get semantics, zero-copy
mmap reads, and eviction/spill when full.

TPU-native design choice: instead of one big mmap'd slab with a custom
allocator, each object is a file under ``/dev/shm`` (tmpfs) mapped on demand.
The kernel's page cache *is* the slab allocator; creation is O(1), reads are
zero-copy ``mmap``, and cross-process attach is by name — which sidesteps
CPython's ``multiprocessing.shared_memory`` resource-tracker unlink hazards
entirely.  A C++ slab store (``native/plasma_store.cc``) is used for
allocation bookkeeping when built; this module is the portable path and the
Python API for both.

Capacity accounting + LRU spill-to-disk live here; *refcounts* live in the
control plane (GCS), which calls ``delete_object`` when counts hit zero.
"""

from __future__ import annotations

import mmap
import os
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Dict, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.exceptions import ObjectStoreFullError

_SHM_DIR = Path(os.environ.get("RTPU_SHM_DIR", "/dev/shm"))
_PREFIX = "rtpu_"


def _seg_path(object_id: str) -> Path:
    return _SHM_DIR / f"{_PREFIX}{object_id}"


class MappedObject:
    """A sealed object mapped read-only; keeps the mmap alive for zero-copy views."""

    __slots__ = ("object_id", "_mm", "_fileobj", "buf")

    def __init__(self, object_id: str, path: Path):
        self.object_id = object_id
        fd = os.open(str(path), os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        self.buf = memoryview(self._mm)

    def close(self) -> None:
        try:
            self.buf.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass  # still-live numpy views pin the map; GC will retry via __del__

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass


def _move_file(src: str, dst: str) -> None:
    """rename, or copy+unlink across filesystems (spill dirs usually live
    on disk while segments live on tmpfs — os.replace alone raises EXDEV)."""
    try:
        os.replace(src, dst)
    except OSError as e:
        import errno
        import shutil
        if e.errno != errno.EXDEV:
            raise
        tmp = dst + ".mv"
        try:
            shutil.copyfile(src, tmp)
            os.replace(tmp, dst)
        except BaseException:
            # a half-written temp (e.g. ENOSPC mid-spill) would eat the
            # very disk space spilling needs — clean it before re-raising
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.unlink(src)


class ShmObjectStore:
    """Node-local store daemon side: create/seal/evict/delete + accounting."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.capacity = capacity_bytes or GLOBAL_CONFIG.object_store_memory_mb * 1024 * 1024
        self.spill_dir = Path(spill_dir) if spill_dir else None
        self._lock = threading.Lock()
        # object_id -> size, LRU order (oldest first); only *sealed* objects.
        self._sealed: "OrderedDict[str, int]" = OrderedDict()   # guarded by: _lock
        self._unsealed: Dict[str, int] = {}                     # guarded by: _lock
        self._spilled: Dict[str, int] = {}                      # guarded by: _lock
        self._used = 0                                          # guarded by: _lock

    # -- creation (writer side) ---------------------------------------------
    def create(self, object_id: str, size: int) -> Tuple[memoryview, object]:
        """Allocate a writable buffer; returns (view, handle). Call seal() after."""
        with self._lock:
            if self._used + size > self.capacity:
                self._evict_locked(self._used + size - self.capacity)
            if self._used + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object of {size} bytes does not fit "
                    f"(used {self._used}/{self.capacity})")
            self._used += size
            self._unsealed[object_id] = size
        path = _seg_path(object_id)
        try:
            fd = os.open(str(path), os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                try:
                    os.ftruncate(fd, max(size, 1))
                    mm = mmap.mmap(fd, max(size, 1),
                                   prot=mmap.PROT_READ | mmap.PROT_WRITE)
                finally:
                    os.close(fd)
            except BaseException:
                # only after a successful O_EXCL open: the segment is
                # OURS to remove (unlinking on an open failure could
                # delete a pre-existing segment of the same name)
                try:
                    os.unlink(str(path))
                except OSError:
                    pass
                raise
        except BaseException:
            # roll back the reservation: a failed create (ENOSPC on a
            # full tmpfs, EEXIST, mmap failure) must not leave _used
            # inflated and a phantom _unsealed entry pinned forever
            with self._lock:
                if self._unsealed.pop(object_id, None) is not None:
                    self._used -= size
            raise
        return memoryview(mm)[:size], mm

    def adopt(self, object_id: str, size: int) -> None:
        """Account for a sealed object another process wrote directly to shm.

        Workers create+seal result objects in /dev/shm themselves (the data
        plane needs no daemon round-trip); the control plane adopts them into
        capacity/LRU accounting when the result metadata arrives.
        """
        with self._lock:
            if object_id in self._sealed or object_id in self._spilled:
                return
            if self._used + size > self.capacity:
                self._evict_locked(self._used + size - self.capacity)
            self._used += size
            self._sealed[object_id] = size

    def seal(self, object_id: str, handle: object) -> None:
        handle.flush() if hasattr(handle, "flush") else None
        with self._lock:
            size = self._unsealed.pop(object_id)
            self._sealed[object_id] = size

    # -- reads (any process; staticmethod: data plane needs no daemon) -------
    @staticmethod
    def map_readonly(object_id: str) -> MappedObject:
        return MappedObject(object_id, _seg_path(object_id))

    @staticmethod
    def exists_in_shm(object_id: str) -> bool:
        return _seg_path(object_id).exists()

    def touch(self, object_id: str) -> None:
        """LRU bump on access."""
        with self._lock:
            if object_id in self._sealed:
                self._sealed.move_to_end(object_id)

    # -- spill / restore -----------------------------------------------------
    def _spill_path(self, object_id: str) -> Path:
        assert self.spill_dir is not None
        return self.spill_dir / f"{_PREFIX}{object_id}"

    def _evict_locked(self, need_bytes: int) -> None:
        if not GLOBAL_CONFIG.object_store_eviction or self.spill_dir is None:
            return
        freed = 0
        victims = []
        for oid, size in self._sealed.items():
            victims.append((oid, size))
            freed += size
            if freed >= need_bytes:
                break
        for oid, size in victims:
            src = _seg_path(oid)
            self.spill_dir.mkdir(parents=True, exist_ok=True)
            _move_file(str(src), str(self._spill_path(oid)))
            del self._sealed[oid]
            self._spilled[oid] = size
            self._used -= size

    def restore(self, object_id: str) -> bool:
        """Bring a spilled object back into shm. True if restored or present."""
        with self._lock:
            if object_id in self._sealed:
                return True
            if object_id not in self._spilled:
                return False
            size = self._spilled[object_id]
            if self._used + size > self.capacity:
                self._evict_locked(self._used + size - self.capacity)
            _move_file(str(self._spill_path(object_id)), str(_seg_path(object_id)))
            del self._spilled[object_id]
            self._sealed[object_id] = size
            self._used += size
            return True

    def location(self, object_id: str) -> str:
        with self._lock:
            if object_id in self._sealed or object_id in self._unsealed:
                return "shm"
            if object_id in self._spilled:
                return "spilled"
            return "missing"

    # -- deletion ------------------------------------------------------------
    def delete_object(self, object_id: str) -> None:
        with self._lock:
            size = self._sealed.pop(object_id, None)
            if size is None:
                size = self._unsealed.pop(object_id, None)
            if size is not None:
                self._used -= size
                try:
                    os.unlink(str(_seg_path(object_id)))
                except FileNotFoundError:
                    pass
                return
            if self._spilled.pop(object_id, None) is not None:
                try:
                    os.unlink(str(self._spill_path(object_id)))
                except FileNotFoundError:
                    pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "used": self._used,
                "num_sealed": len(self._sealed),
                "num_spilled": len(self._spilled),
            }

    def shutdown(self) -> None:
        with self._lock:
            ids = list(self._sealed) + list(self._unsealed) + list(self._spilled)
        for oid in ids:
            self.delete_object(oid)
