"""Always-on per-process flight recorder: a crash-surviving ring buffer.

Black-box recorder in the spirit of the reference's event-stats /
state-dump debugging aids, but built to survive the process: every
ray_tpu process (GCS, workers, driver) appends fixed-size records —
recent wire frames, scheduler dispatch decisions, lock-watchdog waits,
data-plane requests, LLM engine iterations — into a **shared-mmap ring
file in the session directory** (``<session>/flight/<role>_<pid>.ring``).

Because the ring is a ``MAP_SHARED`` file, "dump on crash" needs no
signal handler: a SIGKILLed or OOM-killed process leaves its last
``flight_recorder_slots`` records on disk, exactly as written.  A live
process's ring is equally readable (readers see writes through the page
cache), so ``ray_tpu debug dump`` (GCS op ``debug_dump``) returns the
recent history of every process of the session — dead ones included —
without cooperating with any of them.

Write path (the hot-path budget is a couple of µs):

- ``record(kind, detail)`` takes NO lock: a global ``itertools.count``
  hands out the slot sequence (``next()`` is atomic under the GIL) and
  each record writes only its own slot.  After wrap-around two racing
  writers can theoretically lap each other onto one slot; readers
  detect the torn slot (length bounds / utf-8) and skip it.
- Records are ``[u64 seq][f64 wall-ts][u16 len][utf-8 "kind detail"]``
  in a fixed ``_SLOT_BYTES`` slot; longer details truncate.

Config: ``flight_recorder_enabled`` (default on),
``flight_recorder_slots`` (ring capacity).  DESIGN.md §4h documents the
overwrite semantics.
"""

from __future__ import annotations

import itertools
import os
import struct
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

_MAGIC = b"RTFR1\n\x00\x00"
_HDR = struct.Struct("<8sII Q d")       # magic, slot_size, nslots, pid, t0
_HDR_BYTES = 64
_SLOT = struct.Struct("<Q d H")         # seq, wall ts, payload len
_SLOT_BYTES = 224
_PAY_MAX = _SLOT_BYTES - _SLOT.size

FLIGHT_DIR = "flight"


class FlightRecorder:
    """One process's ring.  Owns the mmap; ``close()`` discharges it
    (the ring FILE stays behind — it is the crash artifact)."""

    def __init__(self, path: str, nslots: int):  # rtlint: owns(path)
        import mmap
        self.path = str(path)
        self.nslots = max(64, int(nslots))
        size = _HDR_BYTES + self.nslots * _SLOT_BYTES
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)  # the mapping keeps the inode; fd not needed
        _HDR.pack_into(self._mm, 0, _MAGIC, _SLOT_BYTES, self.nslots,
                       os.getpid(), time.time())
        self._seq = itertools.count(1)
        self._closed = False

    def record(self, kind: str, detail: str = "") -> None:
        if self._closed:
            return
        seq = next(self._seq)                 # GIL-atomic slot claim
        off = _HDR_BYTES + ((seq - 1) % self.nslots) * _SLOT_BYTES
        pay = (kind + " " + detail if detail else kind).encode(
            "utf-8", "replace")[:_PAY_MAX]
        try:
            _SLOT.pack_into(self._mm, off, seq, time.time(), len(pay))
            self._mm[off + _SLOT.size:off + _SLOT.size + len(pay)] = pay
        except (ValueError, IndexError):
            return  # closed under us / torn geometry: recorder never raises
        if seq % 64 == 0:
            # amortized counter (a per-record tagged inc would put a
            # metric lock on the GCS frame hot path)
            from ray_tpu._private.config import GLOBAL_CONFIG
            if GLOBAL_CONFIG.metrics_enabled:
                from ray_tpu.util import metrics_catalog as mcat
                try:
                    mcat.get("rtpu_trace_flight_records_total").inc(64)
                except Exception:  # noqa: BLE001 - telemetry best-effort
                    pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


_RECORDER: Optional[FlightRecorder] = None
_install_lock = threading.Lock()

# Rings live on tmpfs, NOT in the (disk-backed) session dir: a
# disk-backed shared mapping is subject to writeback, after which the
# next slot write pays a write-protect fault — a host round trip on
# virtualized kernels, ~100µs/record (measured; the recorder's whole
# budget is a couple of µs).  tmpfs pages stay dirty-resident, and
# SIGKILL survival is identical — the file outlives the process either
# way.  One dir per session under _SHM_BASE, reaped by the next
# cluster's install once the owning session's processes are all dead.
_SHM_BASE = "/dev/shm/rtpu_flight"


def flight_dir_for(session_path) -> Path:
    """Where a session's ring files live (tmpfs; session-dir fallback
    for hosts without /dev/shm)."""
    if os.path.isdir("/dev/shm"):
        return Path(_SHM_BASE) / Path(session_path).name
    return Path(session_path) / FLIGHT_DIR


def _reap_orphan_dirs(keep: Path) -> None:
    """Remove other sessions' ring dirs once every recorded pid is dead
    — tier-1 alone creates hundreds of sessions; without this, tmpfs
    grows ~0.5MB per dead process forever."""
    import shutil
    try:
        siblings = list(Path(_SHM_BASE).iterdir())
    except OSError:
        return
    for d in siblings:
        if d == keep or not d.is_dir():
            continue
        alive = False
        try:
            for ring in d.glob("*.ring"):
                if _pid_alive(ring_pid(ring)):
                    alive = True
                    break
        except OSError:
            continue
        if not alive:
            shutil.rmtree(d, ignore_errors=True)


def maybe_install(session_path, role: str) -> Optional[FlightRecorder]:
    """Install the process-wide recorder (idempotent; first caller wins
    within one session — head==driver processes install once as 'gcs').
    Returns the active recorder, or None when disabled / no session."""
    global _RECORDER
    from ray_tpu._private.config import GLOBAL_CONFIG
    if session_path is None or not GLOBAL_CONFIG.flight_recorder_enabled:
        return _RECORDER
    flight_dir = flight_dir_for(session_path)
    with _install_lock:
        if _RECORDER is not None and not _RECORDER._closed:
            if Path(_RECORDER.path).parent == flight_dir:
                return _RECORDER
            _RECORDER.close()   # re-init against a NEW session (tests)
        try:
            flight_dir.mkdir(parents=True, exist_ok=True)
            if role == "gcs":   # one sweep per cluster, not per worker
                _reap_orphan_dirs(flight_dir)
            _RECORDER = FlightRecorder(
                str(flight_dir / f"{role}_{os.getpid()}.ring"),
                GLOBAL_CONFIG.flight_recorder_slots)
        except OSError:
            _RECORDER = None    # recording is best-effort, never fatal
        return _RECORDER


def record(kind: str, detail: str = "") -> None:
    fr = _RECORDER
    if fr is not None:
        fr.record(kind, detail)


def enabled() -> bool:
    return _RECORDER is not None


def close() -> None:
    """Discharge the mmap on clean shutdown (the resource sanitizer
    tracks it); the ring file itself is left behind on purpose."""
    global _RECORDER
    with _install_lock:
        if _RECORDER is not None:
            _RECORDER.close()
            _RECORDER = None


# ------------------------------------------------------------- readers
def read_ring(path) -> List[dict]:
    """Decode one ring file → records in seq order (oldest first).
    Torn/empty slots are skipped; never raises on a malformed ring."""
    try:
        raw = Path(path).read_bytes()
    except OSError:
        return []
    if len(raw) < _HDR_BYTES:
        return []
    try:
        magic, slot_size, nslots, pid, t0 = _HDR.unpack_from(raw, 0)
    except struct.error:
        return []
    if magic != _MAGIC or slot_size <= _SLOT.size or nslots <= 0:
        return []
    out = []
    for i in range(nslots):
        off = _HDR_BYTES + i * slot_size
        if off + _SLOT.size > len(raw):
            break
        try:
            seq, ts, ln = _SLOT.unpack_from(raw, off)
        except struct.error:
            continue
        if seq == 0 or ln > slot_size - _SLOT.size:
            continue  # empty or torn slot
        pay = raw[off + _SLOT.size:off + _SLOT.size + ln]
        text = pay.decode("utf-8", "replace")
        kind, _, detail = text.partition(" ")
        out.append({"seq": seq, "ts": ts, "kind": kind, "detail": detail})
    out.sort(key=lambda r: r["seq"])
    return out


def ring_pid(path) -> Optional[int]:
    try:
        with open(path, "rb") as f:
            hdr = f.read(_HDR_BYTES)
        magic, _, _, pid, _ = _HDR.unpack_from(hdr, 0)
    except (OSError, struct.error):
        return None
    return int(pid) if magic == _MAGIC else None


def collect(session_path, tail: int = 200) -> Dict[str, dict]:
    """Every ring of a session → {ring_name: {pid, alive, records}} with
    the newest ``tail`` records per process.  Dead processes' rings read
    exactly like live ones — that is the point of the recorder."""
    out: Dict[str, dict] = {}
    flight_dir = flight_dir_for(session_path)
    try:
        paths = sorted(flight_dir.glob("*.ring"))
    except OSError:
        return out
    for p in paths:
        recs = read_ring(p)
        pid = ring_pid(p)
        out[p.stem] = {"pid": pid, "alive": _pid_alive(pid),
                       "records": recs[-max(1, int(tail)):]}
    return out


def _pid_alive(pid: Optional[int]) -> bool:
    """EPERM means the pid EXISTS (another user's process) — on a
    shared host that must count as alive, or one user's reap/dump
    would destroy/mislabel another's live session."""
    if not pid:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True
