"""ObjectRef: the distributed future handle.

Reference: ``ObjectID``/``ObjectRef`` in ``python/ray/_raylet.pyx``
(SURVEY.md §2.2/§3.2).  Semantics preserved:

- the ref is a future; ``ray_tpu.get(ref)`` blocks for the value;
- refs are first-class values — passing one to a task defers to its value,
  putting one inside a container keeps it a ref (borrowing tracked at
  serialization time, see ``serialization._RefCollector``);
- dropping the last Python reference releases the distributed refcount
  (``__del__`` → worker.release()).

The *owner* worker id is embedded in the id (``ids.ObjectID``), so borrower
processes know who to report borrows to without a directory hop.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "_worker", "_skip_release", "__weakref__")

    def __init__(self, object_id: str, worker: Optional[object] = None,
                 skip_release: bool = False):
        self.id = ObjectID(object_id)
        self._worker = worker
        self._skip_release = skip_release

    # -- identity ------------------------------------------------------------
    def hex(self) -> str:
        return str(self.id)

    @property
    def owner_id(self) -> str:
        return self.id.owner

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    # -- future sugar ---------------------------------------------------------
    def __await__(self):
        from ray_tpu._private import worker as _w
        # Async actors / serve: run the blocking get in the default executor.
        import asyncio
        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, _w.global_worker().get_one, self)
        return fut.__await__()

    # -- refcount lifecycle ---------------------------------------------------
    @staticmethod
    def _deserialize(object_id: str) -> "ObjectRef":
        return _deserialize_object_ref(object_id)

    def __reduce__(self):
        # Plain-pickle path (refs inside values shipped via cloudpickle
        # outside the framework serializer, e.g. Dataset shards handed to
        # train workers).  The framework serializer's reducer_override
        # additionally records the borrow; here the sender must keep the
        # ref alive (the driver does, via the owning Dataset).
        return (_deserialize_object_ref, (str(self.id),))

    def __del__(self):
        w = self._worker
        if w is not None and not self._skip_release:
            try:
                w.release(str(self.id))
            except Exception:
                pass  # interpreter shutdown / closed control socket


def _deserialize_object_ref(object_id: str) -> ObjectRef:
    """Reconstructs a ref popping out of a pickled value (borrow protocol).

    Module-level so the pickle reduce tuple references a plain importable
    function (bound classmethods don't pickle under protocol-5 reducers).
    """
    from ray_tpu._private import worker as _w
    w = _w.try_global_worker()
    if w is not None:
        w.notify_borrow(object_id)
    return ObjectRef(object_id, worker=w)


# Alias matching the reference's old name.
ObjectRefType = ObjectRef
