"""Worker: the per-process runtime embedded in drivers and workers.

Reference: ``CoreWorker`` (``src/ray/core_worker/``, SURVEY.md §2.1) +
``python/ray/_private/worker.py``.  One ``Worker`` instance per process:

- drivers and task workers both embed it (the reference embeds CoreWorker in
  every process via Cython; ours is pure Python talking to the GCS over the
  control socket and to /dev/shm for data),
- task submission (``submit``) and the ordered direct actor-call path
  (``call_actor`` — reference ``ActorTaskSubmitter``: caller ⇄ actor socket,
  control plane not on the hot path),
- ``get``/``put``/``wait``/``release`` with zero-copy shm reads,
- the executor loop run by worker processes (``run_worker_loop``): normal
  tasks, actor instantiation, the actor method server.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
import time
import traceback
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import data_plane, protocol, rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import KIND_PUT, KIND_RETURN, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import (
    deserialize_from, dumps_call, loads_call, serialize_to_bytes,
)
from ray_tpu._private.session import Session
from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.util import metrics_catalog as mcat
from ray_tpu.util import tracing
from ray_tpu import exceptions as exc

logger = rtlog.get("worker")

_global_worker: Optional["Worker"] = None
_global_lock = threading.Lock()


def global_worker() -> "Worker":
    if _global_worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return _global_worker


def try_global_worker() -> Optional["Worker"]:
    return _global_worker


def set_global_worker(w: Optional["Worker"]) -> None:
    global _global_worker
    with _global_lock:
        _global_worker = w


def _dump_all_stacks() -> str:
    """All-thread stack snapshot of this process (``ray_tpu stack``)."""
    import traceback
    out = []
    for tid, frame in sys._current_frames().items():
        name = next((t.name for t in threading.enumerate()
                     if t.ident == tid), "?")
        out.append(f"--- thread {name} ({tid}) ---\n"
                   + "".join(traceback.format_stack(frame)))
    return "\n".join(out)


def _counter():
    n = [0]
    lock = threading.Lock()

    def nxt() -> int:
        with lock:
            n[0] += 1
            return n[0]
    return nxt


def shm_write_wire(oid: str, wire: bytes, overwrite: bool = False) -> None:
    """Write pre-serialized wire bytes into the object's shm segment.

    The single shm-segment writer: ``put``, task returns, and actor results
    all go through here.  ``overwrite=True`` is for lineage reconstruction,
    which re-creates an object id whose segment may still exist.
    """
    import mmap
    path = f"/dev/shm/rtpu_{oid}"
    flags = os.O_CREAT | os.O_RDWR | (0 if overwrite else os.O_EXCL)
    fd = os.open(path, flags, 0o600)
    try:
        # exact final size up front: an overwrite (reconstruction) may be
        # SMALLER than the old segment — stale tail bytes would corrupt
        # size accounting (store.adopt) and reads
        os.ftruncate(fd, max(len(wire), 1))
        # write() over mmap-and-memcpy: fresh tmpfs pages fault once
        # in-kernel instead of once per user-space touch (~2x)
        mv = memoryview(wire)
        while mv.nbytes:
            mv = mv[os.write(fd, mv):]
    finally:
        os.close(fd)


def shm_write_value(oid: str, pickled: bytes, buffers, *,
                    overwrite: bool = False) -> int:
    """Serialize straight into the object's shm segment with writev —
    the single-copy write path for large objects (buffers → page cache,
    no intermediate wire bytearray).  Returns the segment size."""
    from ray_tpu._private.serialization import write_value_to_fd
    path = f"/dev/shm/rtpu_{oid}"
    flags = os.O_CREAT | os.O_WRONLY | (0 if overwrite else os.O_EXCL)
    fd = os.open(path, flags, 0o600)
    try:
        if overwrite:
            os.ftruncate(fd, 0)
        return write_value_to_fd(fd, pickled, buffers)
    finally:
        os.close(fd)


class _TaskContext(threading.local):
    def __init__(self):
        self.task_id: Optional[str] = None
        self.in_task = False


class Worker:
    def __init__(self, session: Optional[Session], role: str,
                 node_id: Optional[str] = None,
                 proxy_addr: Optional[tuple] = None):
        self.session = session
        self.role = role
        self.worker_id = WorkerID.new()
        self.node_id = node_id
        self.proxy_addr = proxy_addr
        self.is_client = proxy_addr is not None
        # GCS dials ride a bounded jittered backoff on dead-endpoint
        # errors (protocol.connect_retry): a head failover window
        # (standby promoting, socket re-binding — DESIGN.md §4l)
        # surfaces as dial latency, not ConnectionRefusedError.
        if self.is_client:
            # remote-client mode (reference: Ray Client, SURVEY.md §2.3):
            # every connection tunnels through the TCP proxy; no local
            # data plane (see put/_materialize client branches)
            self.gcs_path = "gcs"
            self.pool = protocol.RpcPool(
                self.gcs_path, on_new=self._on_new_channel,
                connect_fn=lambda: protocol.connect_retry(
                    self.gcs_path,
                    connect_fn=lambda: self._tunnel("gcs")))
        else:
            self.gcs_path = session.socket_path("gcs.sock")
            self.pool = protocol.RpcPool(
                self.gcs_path, on_new=self._on_new_channel,
                connect_fn=lambda: protocol.connect_retry(self.gcs_path))
        self._put_seq = _counter()
        self._ret_seq = _counter()
        self._task_seq = _counter()
        self._call_seq = _counter()
        self._dedup_seq = _counter()
        self._fn_cache: Dict[str, Any] = {}
        self._exported: set = set()
        import weakref
        self._fn_id_cache: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        # guarded by: _local_lock
        self._local_values: "OrderedDict[str, bytes]" = OrderedDict()
        self._local_lock = threading.Lock()
        # signaled on every inline-result arrival AND on actor-channel
        # death: get() parks here for in-flight direct calls instead of
        # paying the GCS get_meta machinery (the reader thread loses the
        # race on small hosts, turning every serial actor RT into a full
        # control-plane round-trip — measured 2x the direct-path latency)
        self._local_cv = threading.Condition(self._local_lock)
        # guarded by: _actor_chan_lock
        self._actor_channels: Dict[str, "_ActorChannel"] = {}
        self._actor_chan_lock = threading.Lock()
        # in-flight chunked pulls                guarded by: _pull_lock
        self._pulls: Dict[str, dict] = {}
        self._pull_lock = threading.Lock()
        # Batched ObjectRef drops, buffered PER THREAD and flushed on the
        # owning thread's (thread-local) channel.  This preserves the exact
        # per-channel FIFO the unbatched code had — a release always lands
        # on the same channel as, and after, that thread's earlier submits,
        # so a pipelined `put(x); f.remote(r); del r` can never have its
        # decref overtake the submit on another connection and free the dep
        # before the GCS sees the task.  (Deferral only ever delays a
        # release — the safe direction.)  The registry exists so shutdown
        # can drain buffers of threads that went idle; RLock because
        # release() runs from __del__ and an in-lock allocation can
        # trigger cyclic GC that re-enters on the same thread.
        self._release_tls = threading.local()
        # guarded by: _release_lock
        self._release_bufs: Dict[int, List[str]] = {}
        self._release_lock = threading.RLock()
        # Client-side pin/release netting (actor-call return refs ONLY —
        # refs whose seal is concurrent with the pin, so the GCS's 10s
        # rc-0-at-seal grace covers the parked window): a pin buffers
        # here and a release of the same oid CANCELS it before either
        # becomes a message — the get-and-drop hot loop then sends no
        # refcount traffic at all.  Still-held pins are drained onto the
        # ordered submit stream by the flusher's idle tick within ~1s.
        # Guarded by _release_lock (same __del__ reentrancy rules as the
        # release buffers).
        # guarded by: _release_lock
        self._pending_pins: Dict[str, int] = {}
        # return-oid → (actor_id, call_id) for in-flight actor calls: a
        # result observed through ANY path (inline reply, GCS get) marks
        # the call complete, so a racing disconnect can't resubmit an
        # already-executed method (see _ActorChannel._on_disconnect)
        self._inflight_calls: Dict[str, Tuple[str, str]] = {}
        # Pipelined submit batching (reference: lease-cached submission +
        # the r2 release batching): specs buffer here and ship 64-to-a-
        # message.  Ordering contract: any release referencing a buffered
        # spec's deps must flush AFTER the spec — release paths call
        # _flush_submits() first.  Out-of-order put_object vs submit is
        # safe (the GCS promotes dep-waiters when the object arrives).
        # interleaved specs + releases          guarded by: _submit_lock
        self._submit_buf: List[Any] = []
        self._submit_lock = threading.Lock()
        # serializes pop→send in _drain_submits: without it two threads
        # (64-full caller vs flusher) could pop successive batches and
        # reach the wire in either order, letting a release overtake the
        # submit whose dep pin it retires.  Ordering: acquire BEFORE
        # _submit_lock, never the reverse.
        self._submit_send_lock = threading.Lock()
        self._submit_first: float = 0.0
        self._submit_flusher_on = False
        # event-driven flusher wakeup: set when something buffers, cleared
        # when the buffer drains — an idle process must not pay 500
        # scheduler wakeups/s for an empty-buffer poll loop (measured
        # contention on 1-2 core hosts)
        self._submit_pending = threading.Event()
        # revoked (task_id, dseq) pairs, insertion-ordered so overflow
        # evicts the OLDEST revocation (an arbitrary set.pop could evict
        # the pair a drop_queued just added, un-revoking it)
        self._dropped_ids: "OrderedDict[tuple, None]" = OrderedDict()
        self._oneway_chan: Optional[protocol.RpcChannel] = None
        self._oneway_init_lock = threading.Lock()
        # Owner-based lineage across head restarts (reference: TaskManager
        # lives in the OWNING worker): every submitted spec is retained
        # until one of its returns is observed terminal or its refs are
        # all released; on reconnect to a RESTARTED head (epoch change)
        # the owner resubmits the survivors — a head crash must not
        # strand a caller's get() forever.
        # guarded by: _owned_lock
        self._owned_specs: "OrderedDict[str, dict]" = OrderedDict()
        # return oid -> task_id                  guarded by: _owned_lock
        self._owned_by_ret: Dict[str, str] = {}
        self._owned_lock = threading.Lock()
        self._gcs_epoch: Optional[str] = None
        self._pull_sem = threading.Semaphore(
            max(1, GLOBAL_CONFIG.transfer_max_inflight))
        # pooled data-plane connections for peer pulls (dial+HMAC paid
        # once per holder, not once per object); thread-safe internally
        self._data_pool = data_plane.DataPlanePool(dial=self._dial_data)
        # Raylet attachment (DESIGN.md §4i): spawned workers on a raylet
        # node dial the LOCAL per-node scheduler for their task/ctl
        # channels and route release oneways to it for netting, instead
        # of tunneling every frame to the head.  Absent env (no raylet
        # advertised — single-process tests, cluster_utils.Cluster,
        # legacy agents) → direct-GCS, byte-identical to before.
        self.raylet_sock = (os.environ.get("RTPU_RAYLET_SOCK")
                            if role == "worker" else None)
        self._raylet_ref_conn = None
        self._raylet_ref_lock = threading.Lock()
        self.ctx = _TaskContext()
        self._pid = os.getpid()  # cached: getpid is a real syscall per call
        self._ctl_down = True    # flipped by the ctl thread on attach
        self._task_conn = None
        self._task_conn_lock = threading.Lock()
        self._actor_announce: Optional[dict] = None  # set in _become_actor
        self._current_spec: Optional[dict] = None
        self._exec_thread_id: Optional[int] = None
        self._stop = threading.Event()
        self._profile_events: List[dict] = []
        self._slab = None          # native slab store attachment (lazy)
        self._slab_tried = self.is_client  # clients have no local data plane
        # registration happens on first channel creation
        info = self.pool.call("register_client", role=role,
                              client_id=self.worker_id, pid=os.getpid(),
                              node_id=node_id)
        self.node_id = info["node_id"]
        if self._gcs_epoch is None:
            self._gcs_epoch = info.get("epoch")
        if session is not None and not self.is_client:
            # crash-surviving flight recorder (DESIGN.md §4h); in the
            # head==driver process the GCS already installed one and
            # this is a no-op (first installer of a session wins)
            from ray_tpu._private import flight_recorder
            flight_recorder.maybe_install(session.path, role)
        if not self.is_client:
            # always-on sampling profiler (DESIGN.md §4o); same
            # first-installer-wins idempotence as the flight recorder,
            # deltas ride the metrics publisher below
            from ray_tpu.util import profiler as profiler_mod
            profiler_mod.maybe_install(role)
        self._start_metrics_publisher()

    # ------------------------------------------------------ metrics publisher
    def _start_metrics_publisher(self) -> None:
        """Always-on telemetry (reference: the per-node metrics agent's
        export loop): a daemon thread pushes this process's metric
        registry to the GCS KV every ``metrics_export_period_s`` so
        `/metrics` and `ray_tpu metrics` show live data with zero user
        wiring.  Off the task hot path by construction — one kv_put per
        period (>= 1s), nothing per task.  Clients skip it: they have no
        built-in instrumentation and every publish would tunnel through
        the head proxy."""
        if self.is_client or not GLOBAL_CONFIG.metrics_enabled:
            return
        threading.Thread(target=self._metrics_publish_loop,
                         name="metrics-publisher", daemon=True).start()

    def _metrics_publish_loop(self) -> None:
        import random

        from ray_tpu.util import metrics as metrics_mod
        period = max(1.0, GLOBAL_CONFIG.metrics_export_period_s)
        err_logged = False
        # jittered: a fleet of workers forked together must not land
        # synchronized kv_puts on the head every period
        from ray_tpu.util import profiler as profiler_mod
        while not self._stop.wait(period * random.uniform(0.75, 1.25)):
            try:
                metrics_mod.publish(self)
                # the profiler's folded-stack delta rides the same
                # cadence and connection (§4o) — one more kv_put per
                # period, nothing per task
                profiler_mod.publish(self)
                err_logged = False
            except Exception:  # noqa: BLE001 - head restarting / shutting
                # down: telemetry must never take a process with it; the
                # next cycle retries against the healed control plane.
                # Logged (once per failure streak) because the cause may
                # be PERSISTENT — e.g. a user metric whose tag value
                # json.dumps can't serialize — and a silently dark
                # process is undiagnosable.
                if self._stop.is_set():
                    return
                if not err_logged:
                    err_logged = True
                    logger.warning("metrics publish failed (will keep "
                                   "retrying every %.0fs)", period,
                                   exc_info=True)

    def _final_metrics_flush(self) -> None:
        """One last publish on clean shutdown so short-lived processes'
        series (e.g. a task worker that just finished) are visible."""
        if self.is_client or not GLOBAL_CONFIG.metrics_enabled:
            return
        try:
            from ray_tpu.util import metrics as metrics_mod
            metrics_mod.publish(self)
        except Exception:  # noqa: BLE001 - control plane already gone
            pass
        try:
            from ray_tpu.util import profiler as profiler_mod
            profiler_mod.publish(self)
        except Exception:  # noqa: BLE001 - control plane already gone
            pass

    # ------------------------------------------------------------- plumbing
    def _on_new_channel(self, ch: protocol.RpcChannel) -> None:
        # Every extra thread-local channel re-registers (idempotent server-side)
        if getattr(self, "node_id", None) is not None:
            info = ch.call("register_client", role=self.role,
                           client_id=self.worker_id,
                           pid=os.getpid(), node_id=self.node_id)
            epoch = info.get("epoch")
            if self._gcs_epoch is None:
                self._gcs_epoch = epoch
            elif epoch is not None and epoch != self._gcs_epoch:
                # a DIFFERENT head: its task table died with the old one —
                # resubmit every owned in-flight spec (at-least-once; a
                # surviving worker's late result for the same task seals
                # the same return ids, which the seal path tolerates)
                self._gcs_epoch = epoch
                # the new head's per-worker dispatch sequences restart:
                # stale revocations must not swallow re-dispatched tasks
                self._dropped_ids.clear()
                self._resubmit_owned(ch)

    # Two-way RPC kinds that MUTATE server state: these carry a _dedup id
    # so the one post-reconnect retry is exactly-once against a still-live
    # GCS (channel broke after apply, before the reply).  Reads are
    # idempotent and excluded — caching their replies would pin bulk data
    # (fetch_chunk carries multi-MB payloads) on the head for no benefit.
    # One-way mutations (submit_task/add_refs/release*) are never retried
    # by this path and need no dedup.
    _DEDUP_KINDS = frozenset({
        "put_object", "put_chunk", "create_actor", "kill_actor",
        "export_function", "seal_errors", "kv_put", "kv_del",
        "pg_create", "pg_remove", "add_node", "remove_node"})

    def _local_server(self):
        """The GcsServer living in THIS process (head == driver), if it is
        the one this worker is attached to — the in-process dispatch
        short-circuit.  None for spawned workers, clients, and drivers
        attached to an external head."""
        if self.is_client:
            return None
        from ray_tpu._private import gcs as gcs_mod
        srv = gcs_mod._INPROC_SERVER
        if srv is not None and not srv._shutdown and not srv._fenced \
                and srv.rpc_path == self.gcs_path:
            # _fenced: a promoted standby claimed the ledger (§4l) —
            # fall through to the socket path, which re-dials gcs.sock
            # and lands on the NEW head's re-bound listener
            return srv
        return None

    def rpc(self, kind: str, _reconnect: bool = True, **fields: Any) -> dict:
        # Two-way calls observe prior submits (FIFO illusion): flush the
        # submit batch first — e.g. a get_meta on a buffered task's return
        # must find the task registered.
        if self._submit_buf:
            self._flush_submits()
        srv = self._local_server()
        if srv is not None:
            return srv.local_call(
                kind, {"kind": kind, "client_id": self.worker_id, **fields})
        # Across a true GCS restart the dedup cache is empty and the retry
        # re-applies — the documented at-least-once contract for head
        # fault tolerance (fresh object table).  A counter suffices: the
        # server's dedup key is (client_id, id) and client ids are unique
        # per process (uuid4 here cost ~30µs per put on small hosts).
        if kind in self._DEDUP_KINDS:
            fields["_dedup"] = self._dedup_seq()
        try:
            return self.pool.call(kind, client_id=self.worker_id, **fields)
        except (EOFError, OSError, ConnectionError):
            # GCS conn lost (head crash/restart).  Reconnect with grace and
            # re-issue ONCE (reference: retryable gRPC clients + raylets
            # reconnecting to a restarted GCS).  _reconnect=False callers
            # (best-effort telemetry) must never drive the heal themselves:
            # a background pool.invalidate() can yank a channel the MAIN
            # thread's reconnect dance just re-established.  Remote-agent
            # WORKERS (is_client but role=worker) do heal: on a raylet
            # node the task conn is local and never notices a head
            # restart, so the tunneled rpc pool must reconnect on its
            # own — only interactive CLIENTS surface the break.
            if (self.is_client and self.role != "worker") \
                    or self._stop.is_set() or not _reconnect:
                raise
            self._reconnect_pool()
            return self.pool.call(kind, client_id=self.worker_id, **fields)

    def _reconnect_pool(self) -> None:
        """Re-dial the GCS socket until it answers or the grace expires.
        A fresh channel re-registers via the pool's on_new hook."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        deadline = time.monotonic() + GLOBAL_CONFIG.gcs_reconnect_timeout_s
        logger.warning("lost GCS connection; retrying for up to %.0fs",
                       GLOBAL_CONFIG.gcs_reconnect_timeout_s)
        while not self._stop.is_set():
            self.pool.invalidate()
            self._oneway_chan = None  # the ordered oneway channel too
            try:
                self.pool.channel()
                logger.info("reconnected to GCS")
                return
            except (EOFError, OSError, ConnectionError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        raise ConnectionError("worker stopping during GCS reconnect")

    def rpc_oneway(self, kind: str, **fields: Any) -> None:
        """One-way sends ride ONE shared channel (RpcChannel serializes
        sends internally), so every oneway in this process is globally
        FIFO at the server: a release can never overtake the submit whose
        dep pin it retires even when different threads (e.g. the submit
        flusher vs the GC) issue them.

        In-process head: apply inline instead (strictly program-ordered —
        stronger than the channel FIFO); handler errors are logged, not
        raised, matching the socket path's fire-and-forget contract."""
        srv = self._local_server()
        if srv is not None:
            try:
                srv.local_call(kind, {"kind": kind, "rid": None,
                                      "client_id": self.worker_id, **fields})
            except Exception:  # noqa: BLE001 - oneway: log like the server
                logger.exception("local one-way rpc %s failed", kind)
            return
        if self.raylet_sock is not None and kind in (
                "release", "release_batch"):
            # owner-local refcount batch (§4i): the raylet nets these and
            # reconciles to the GCS ledger asynchronously.  Releases only
            # — delaying a release is categorically safe (it can only
            # delay a free); pins keep their direct-channel ordering.
            if self._send_raylet_ref(kind, fields):
                return
            # raylet gone (node tearing down): fall through to direct
        ch = self._oneway_chan
        if ch is None:
            with self._oneway_init_lock:
                ch = self._oneway_chan
                if ch is None:
                    ch = protocol.RpcChannel(self.open_conn(self.gcs_path),
                                             negotiate=True)
                    self._oneway_chan = ch
        try:
            ch.send_oneway(kind, client_id=self.worker_id, **fields)
        except (OSError, ValueError, ConnectionError):
            self._oneway_chan = None  # re-dial on next use
            raise

    def _send_raylet_ref(self, kind: str, fields: dict) -> bool:
        """Ship one release oneway to the local raylet's netting buffer.
        Returns False (caller falls back to the direct channel) when the
        raylet socket is unreachable."""
        with self._raylet_ref_lock:
            conn = self._raylet_ref_conn
            try:
                if conn is None:
                    conn = protocol.connect(self.raylet_sock)
                    self._raylet_ref_conn = conn  # owned before any send
                    conn.send({"kind": "ref_chan",
                               "worker_id": self.worker_id})
                conn.send({"kind": kind, "client_id": self.worker_id,
                           **fields})
                return True
            except (OSError, ValueError, EOFError):
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._raylet_ref_conn = None
                return False

    def _tunnel(self, target: str):
        """Open a proxied connection to a cluster-local unix socket."""
        return protocol.tunnel_connect(*self.proxy_addr, target)

    def open_conn(self, addr: str):
        """Connect to a cluster socket directly or via the client proxy.

        ``tcp://host:port`` addresses (actors on remote-agent hosts) are
        dialed directly with a bounded connect+handshake — an unreachable
        host must fail in seconds, not the OS SYN-retry window.  Proxied
        processes fall back to the head proxy dialing out on their behalf
        (hub-spoke topologies where sibling hosts can't reach each
        other); head-side callers have no such relay — an agent behind
        NAT can run tasks but its actors are only callable from hosts
        that can route to it (documented in DESIGN.md)."""
        tcp = protocol.parse_tcp_addr(addr)
        if self.is_client:
            if tcp is not None:
                try:
                    return protocol.connect_addr(addr, timeout=3.0)
                except (OSError, ConnectionError):
                    pass
            return self._tunnel(addr)
        if tcp is not None:
            return protocol.connect_addr(addr, timeout=3.0)
        if addr == self.gcs_path:
            # head socket: cover the failover re-bind window (§4l)
            return protocol.connect_retry(addr)
        return protocol.connect_addr(addr)

    def _dial_data(self, addr: str):
        """Data-plane dial for the connection pool: (conn, raw).

        ``raw=True`` only for a DIRECT tcp connection — bulk frames ride
        the socket fd itself (sendfile / recv_into).  A tunneled
        connection crosses the head proxy's message pump, which re-frames
        Connection messages, so bulk frames must ride ``send_bytes``
        messages there (same ladder as :meth:`open_conn`)."""
        tcp = protocol.parse_tcp_addr(addr)
        if tcp is not None:
            if self.is_client:
                try:
                    return protocol.connect_data(*tcp, timeout=3.0), True
                except (OSError, ConnectionError):
                    return self._tunnel(addr), False
            return protocol.connect_data(*tcp, timeout=3.0), True
        if self.is_client:
            return self._tunnel(addr), False
        return protocol.connect_addr(addr), False

    def _send_event(self, msg: dict) -> None:
        with self._task_conn_lock:
            if self._task_conn is not None:
                try:
                    self._task_conn.send(msg)
                except (OSError, ValueError):
                    pass

    @property
    def slab(self):
        """Attachment to the session's native slab store (None if absent)."""
        if not self._slab_tried:
            self._slab_tried = True
            if GLOBAL_CONFIG.use_native_store:
                from ray_tpu.native import SlabStore
                self._slab = SlabStore.attach(self.session.slab_path())
        return self._slab

    def _write_wire(self, oid: str, wire: bytes, overwrite: bool = False) -> str:
        """Store wire bytes on the data plane; returns the loc recorded in the
        object's metadata.  Small → native slab (one futex + memcpy, no
        daemon traffic); large → own tmpfs segment (zero-copy mmap reads)."""
        slab = self.slab
        if slab is not None and len(wire) <= GLOBAL_CONFIG.slab_object_max_bytes:
            if overwrite:
                slab.delete(oid)  # reconstruction re-creates the id
            if slab.put(oid, wire):
                return "slab"
            # slab full / out of slots → fall through to file-per-object
        shm_write_wire(oid, wire, overwrite=overwrite)
        return "shm"

    # ------------------------------------------------------------ put / get
    def put(self, value: Any, _owner_kind: str = KIND_PUT) -> ObjectRef:
        from ray_tpu._private.serialization import (serialize,
                                                    serialized_size,
                                                    to_wire_bytes)
        # deferred decrefs must land before allocating: a put loop that
        # drops its previous refs would otherwise fill the store with
        # garbage and force spills instead of deletes
        self._flush_releases()
        oid = ObjectID.make(self.worker_id, _owner_kind, self._put_seq())
        pickled, buffers, refs = serialize(value)
        size = serialized_size(pickled, buffers)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_object_store_put_bytes").inc(size)
        contained = [str(r.id) for r in refs]
        slab = self.slab
        tiny = size <= GLOBAL_CONFIG.inline_object_max_bytes or \
            (self.is_client and size <= GLOBAL_CONFIG.transfer_chunk_bytes)
        wire_cache = []

        def wire():  # assemble at most once across the branch chain
            if not wire_cache:
                wire_cache.append(to_wire_bytes(pickled, buffers))
            return wire_cache[0]

        if self.is_client and not tiny:
            loc = self._spool_or_upload(str(oid), pickled, buffers)
            self.rpc("put_object", object_id=str(oid), loc=loc,
                     size=size, contained=contained, node_id=self.node_id)
        elif slab is not None and size <= GLOBAL_CONFIG.slab_object_max_bytes \
                and slab.put(str(oid), wire()):
            self.rpc("put_object", object_id=str(oid), loc="slab",
                     size=size, contained=contained, node_id=self.node_id)
        elif tiny:
            # no slab, or slab full/out of slots: tiny objects ride the RPC
            self.rpc("put_object", object_id=str(oid), loc="inline",
                     data=wire(),
                     size=size, contained=contained, node_id=self.node_id)
        else:
            # single-copy path: buffers stream straight into the segment
            shm_write_value(str(oid), pickled, buffers)
            self.rpc("put_object", object_id=str(oid), loc="shm", size=size,
                     contained=contained, node_id=self.node_id)
        return ObjectRef(str(oid), worker=self)

    def _materialize(self, oid: str, meta: dict) -> Any:
        value = self._materialize_value(oid, meta)
        # counted AFTER the bytes were actually obtained: a failed fetch
        # (or a slab-miss retry re-entering here) must not inflate the
        # counter with bytes that were never delivered
        if GLOBAL_CONFIG.metrics_enabled:
            size = meta.get("size") or (len(meta["data"])
                                        if meta.get("data") is not None else 0)
            if size:
                mcat.get("rtpu_object_store_get_bytes").inc(size)
        return value

    def _materialize_value(self, oid: str, meta: dict) -> Any:
        if meta["state"] == "error":
            err = deserialize_from(memoryview(meta["data"]))
            raise err
        if meta["loc"] == "inline":
            return deserialize_from(memoryview(meta["data"]))
        if meta["loc"] == "remote":
            # spooled on a sibling host's data plane (P2P object plane)
            return deserialize_from(self._fetch_peer_object(oid, meta))
        if self.is_client and meta["loc"] in ("slab", "shm", "spilled"):
            return deserialize_from(self._fetch_remote_wire(oid))
        if meta["loc"] == "slab":
            slab = self.slab
            data = slab.get(oid) if slab is not None else None
            if data is None:
                # vanished between meta reply and read → same recovery path
                # as a lost tmpfs segment
                raise FileNotFoundError(oid)
            return deserialize_from(memoryview(data))
        mapped = ShmObjectStore.map_readonly(oid)
        return deserialize_from(mapped.buf)

    def _fetch_peer_object(self, oid: str, meta: dict) -> memoryview:
        """Read a remote-spooled object: same-host spool file directly,
        else dial the holder's data plane (direct, or through the head
        proxy for unreachable peers — open_conn's ladder), else fall back
        to the head relay, which pulls the object through itself
        (reference: PullManager direct-pull with relay fallback)."""
        spool = os.environ.get("RTPU_SPOOL_DIR")
        if spool and meta.get("node_id") == self.node_id:
            try:
                return memoryview(
                    data_plane.spool_path(spool, oid).read_bytes())
            except OSError:
                pass  # spool lost locally: try the network paths
        addr = meta.get("addr")
        if addr:
            with self._pull_sem:
                try:
                    return memoryview(self._data_pool.pull(
                        addr, oid, size=meta.get("size")))
                except (OSError, EOFError, ConnectionError,
                        FileNotFoundError):
                    pass  # unreachable holder: head relay below
        t0 = time.monotonic()
        t0w = time.time()
        data = self._fetch_remote_wire(oid)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_data_pull_seconds").observe(
                time.monotonic() - t0, tags={"path": "relay"})
        span = tracing.current_span()
        if span is not None and span.sampled:
            # relay-path leg of the request tree (the direct-pull span is
            # emitted inside DataPlanePool.pull, bytes/path tagged there)
            tracing.emit_span("data.pull", span, t0w,
                              time.monotonic() - t0, cat="data",
                              bytes=len(data), path="relay", object_id=oid)
        return data

    def _fetch_remote_wire(self, oid: str) -> memoryview:
        """Pull one object's wire bytes over the control plane (the
        cross-host data path).  Large objects stream in
        ``transfer_chunk_bytes`` pieces; concurrent pulls of the SAME
        object coalesce onto one in-flight transfer (reference:
        PullManager dedup), and ``transfer_max_inflight`` bounds how many
        chunked pulls run at once (bandwidth admission)."""
        with self._pull_lock:
            inflight = self._pulls.get(oid)
            if inflight is None:
                inflight = {"ev": threading.Event(), "wire": None, "err": None}
                self._pulls[oid] = inflight
                leader = True
            else:
                leader = False
        if not leader:
            inflight["ev"].wait()
            if inflight["err"] is not None:
                raise inflight["err"]
            return memoryview(inflight["wire"])
        try:
            wire = self._pull_object(oid)
            inflight["wire"] = wire
            return memoryview(wire)
        except BaseException as e:
            inflight["err"] = e
            raise
        finally:
            with self._pull_lock:
                self._pulls.pop(oid, None)
            inflight["ev"].set()

    def _pull_object(self, oid: str):
        resp = self.rpc("fetch_object", object_id=oid)
        data = resp.get("data")
        if data is not None:
            return data
        if not resp.get("chunked"):
            raise FileNotFoundError(oid)  # lost → reconstruction retry
        size = resp["size"]
        chunk = GLOBAL_CONFIG.transfer_chunk_bytes
        buf = bytearray(size)
        with self._pull_sem:
            off = 0
            while off < size:
                r = self.rpc("fetch_chunk", object_id=oid, offset=off,
                             length=min(chunk, size - off))
                piece = r.get("data")
                if not piece:
                    raise FileNotFoundError(oid)
                buf[off:off + len(piece)] = piece
                off += len(piece)
        return buf

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [str(r.id) for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        metas: Dict[str, dict] = {}
        missing = []
        with self._local_lock:
            for oid in oids:
                data = self._local_values.get(oid)
                if data is not None:
                    metas[oid] = {"state": "ready", "loc": "inline", "data": data}
                else:
                    missing.append(oid)
        if missing:
            missing = self._await_inline_results(missing, metas, deadline)
        if missing:
            metas.update(self._blocking_get_meta(missing, deadline))
        # any meta observed at a terminal state completes its actor call
        # (the inline reply may have died with the actor; see
        # _mark_call_done)
        if self._inflight_calls:
            for oid, meta in metas.items():
                if meta.get("state") in ("ready", "error"):
                    self._mark_call_done(oid)
        if self._owned_by_ret:
            # terminal returns release the owner-side lineage retention
            for oid, meta in metas.items():
                if meta.get("state") in ("ready", "error"):
                    self._untrack_owned_ret(oid)
        out = []
        for oid in oids:
            for attempt in range(3):
                try:
                    out.append(self._materialize(oid, metas[oid]))
                    break
                except FileNotFoundError:
                    # segment vanished between meta reply and mmap (loss or
                    # eviction race): re-resolve, which triggers
                    # reconstruction server-side
                    if attempt == 2:
                        raise exc.ObjectLostError(oid, "shm segment vanished")
                    metas.update(self._blocking_get_meta([oid], deadline))
        return out

    def _await_inline_results(self, missing: List[str], metas: dict,
                              deadline: Optional[float]) -> List[str]:
        """Direct-call fast path: when EVERY missing ref is the return of
        an in-flight actor call on a live direct channel, park on the
        inline-reply arrival instead of doing a GCS get_meta.

        The reply lands on the channel reader thread; on small hosts the
        reader reliably loses the race with the caller's get(), which then
        pays the full control-plane round-trip (waiter registration, seal
        event, reply encode) for a result that was already on its way —
        measured 2x the direct-path serial latency.  Falls back to the
        authoritative GCS path the moment any ref is not inline-eligible
        (big results arrive seal-only, dead channels seal errors there).
        Returns the refs still needing the GCS."""
        if self.ctx.in_task:
            # inside a task the GCS path is mandatory: it releases this
            # worker's CPU while blocked (task_blocked) so the scheduler
            # can run whatever the awaited call depends on — parking here
            # instead can deadlock a fully-occupied host
            return missing
        flushed = False
        while True:
            with self._local_cv:
                found = [o for o in missing if o in self._local_values]
                for o in found:
                    metas[o] = {"state": "ready", "loc": "inline",
                                "data": self._local_values[o]}
                if found:
                    missing = [o for o in missing
                               if o not in self._local_values]
                if not missing:
                    return []
            with self._actor_chan_lock:
                for oid in missing:
                    ent = self._inflight_calls.get(oid)
                    ch = self._actor_channels.get(ent[0]) if ent else None
                    if ch is None or ch.closed:
                        return missing  # not inline-eligible → GCS
            if not flushed:
                # this wait turned out to be a real block: deferred
                # decrefs must not pin store memory for a long actor
                # method (same contract as _blocking_get_meta) — but
                # only pay the flush once we actually block, not on the
                # already-arrived hot path
                flushed = True
                self._flush_releases()
                continue  # the flush may have taken a while: re-check
            with self._local_cv:
                if any(o in self._local_values for o in missing):
                    continue  # arrived between the two locks
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    return missing  # GCS path raises GetTimeoutError
                # bounded slice: re-checks channel liveness/in-flight
                # membership above even on a missed notify
                self._local_cv.wait(0.05)

    def _blocking_get_meta(self, oids: List[str],
                           deadline: Optional[float]) -> dict:
        """get_meta RPC that (a) releases this task's CPU while blocked so
        dependency/reconstruction tasks can schedule, and (b) honors the
        caller's overall deadline across retries."""
        remaining = None
        if deadline is not None:
            remaining = max(0.0, deadline - time.monotonic())
        blocked = self.ctx.in_task
        if blocked:
            # fast path first: task args are usually already sealed, and
            # the task_blocked → CPU-release → pump → task_unblocked dance
            # for a get that never actually waits both over-dispatches the
            # scheduler (blocked workers don't count against the spawn
            # cap) and storms the pump (measured on the 100KB-arg loop)
            resp = self.rpc("get_meta", object_ids=oids, nonblock=True)
            if "metas" in resp:
                return resp["metas"]
            self._send_event({"kind": "task_blocked"})
        # deferred decrefs must land before a potentially-long block,
        # or they pin store memory for the whole wait
        self._flush_releases()
        try:
            resp = self.rpc("get_meta", object_ids=oids, timeout=remaining)
        finally:
            if blocked:
                self._send_event({"kind": "task_unblocked"})
        return resp["metas"]

    def get_one(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        return self.get([ref], timeout=timeout)[0]

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError(
                f"num_returns ({num_returns}) > number of refs ({len(refs)})")
        # flush-before-block invariant: buffered decrefs must not pin dead
        # objects for the duration of a possibly-indefinite wait
        self._flush_releases()
        by_id = {str(r.id): r for r in refs}
        with self._local_lock:
            local_ready = [oid for oid in by_id if oid in self._local_values]
        if len(local_ready) >= num_returns:
            ready_set = set(local_ready[:num_returns])
            return ([r for o, r in by_id.items() if o in ready_set],
                    [r for o, r in by_id.items() if o not in ready_set])
        resp = self.rpc("wait", object_ids=list(by_id), num_returns=num_returns,
                        timeout=timeout)
        ready = [by_id[o] for o in resp["ready"]]
        not_ready = [by_id[o] for o in resp["not_ready"]]
        return ready, not_ready

    def _release_buf(self) -> List[str]:
        buf = getattr(self._release_tls, "buf", None)
        if buf is None:
            buf = self._release_tls.buf = []
            with self._release_lock:
                stale = self._release_bufs.get(threading.get_ident())
                if stale:
                    # CPython reuses thread idents: adopt a dead thread's
                    # unflushed drops instead of orphaning them forever
                    buf.extend(stale)
                self._release_bufs[threading.get_ident()] = buf
        return buf

    def release(self, oid: str) -> None:
        """Drop one client reference (ObjectRef.__del__).

        Batched per thread: dropping N refs costs N/64 control-plane
        messages, not N (measured 0.3ms/message on the submit hot loop).
        Flushing on the dropping thread's own channel keeps the exact
        submit→release FIFO of the unbatched path — see the buffer's
        declaration comment for the ordering argument."""
        if self._stop.is_set():
            return
        if self._owned_by_ret:
            self._untrack_owned_ret(oid)  # owner dropped the return ref
        buf = self._release_buf()
        with self._release_lock:  # RLock: cyclic-GC re-entry safe
            n = self._pending_pins.get(oid)
            # net only for inline-cached (small) results: the pair then
            # costs zero messages and the object's 10s graceful-free
            # retention holds only bytes the control plane already
            # carried.  A BIG (non-inline) result must free promptly —
            # ship its pin onto the stream NOW (so this release can
            # never overtake it) and send the release normally.
            # _local_values membership is a GIL-atomic dict read; taking
            # _local_lock here could invert against a __del__ fired
            # inside cache_local.
            if n and oid in self._local_values:
                # cancels a not-yet-flushed pin: the pair nets to zero
                # messages (the actor-call get-and-drop hot loop)
                if n == 1:
                    del self._pending_pins[oid]
                else:
                    self._pending_pins[oid] = n - 1
                return
            if n:
                self._drain_pending_pins()  # re-entrant under _release_lock
            buf.append(oid)
            if len(buf) < 64:
                return
            batch = buf[:]
            del buf[:]
        # buffered submits pin deps these releases may drop: submits first
        if self._submit_buf:
            self._flush_submits()
        self.rpc_oneway("release_batch", object_ids=batch)

    def _flush_releases(self, all_threads: bool = False) -> None:
        """Drain THIS thread's release buffer (called before blocking
        waits and puts so deferred decrefs don't pin store memory).
        ``all_threads`` (shutdown only) drains every thread's buffer on
        the calling thread — cross-channel ordering no longer matters
        once nothing new can be submitted."""
        if self._submit_buf or (all_threads and self._pending_pins):
            # submits pin deps; they must land first.  Pins alone don't
            # gate a block (they only add protection; flusher tick covers
            # them) — except at shutdown, when this is the last chance.
            self._flush_submits()
        batches: List[List[str]] = []
        with self._release_lock:  # copy+clear must be atomic vs shutdown
            buf = getattr(self._release_tls, "buf", None)
            if buf:
                batches.append(buf[:])
                del buf[:]
            if all_threads:
                for b in self._release_bufs.values():
                    if b:
                        batches.append(b[:])
                        del b[:]
        for batch in batches:
            if self._stop.is_set():
                return
            try:
                self.rpc_oneway("release_batch", object_ids=batch)
            except (OSError, ConnectionError, EOFError):
                return

    def notify_borrow(self, oid: str) -> None:
        """Pin a borrowed (deserialized nested) ref for this client.  Rides
        the ordered submit stream (one buffered op, flushed within ~2ms —
        not a oneway message per borrow); its later release flushes the
        stream first, so the pin always applies before the unpin.  NOT
        routed through the netted-pin buffer: a borrowed object is
        usually long-sealed, so the rc-0-at-seal grace does not protect
        it — another holder's release during a parked pin's window would
        free the data (the netting path is only safe for refs whose seal
        is concurrent with the pin, i.e. actor-call returns)."""
        if not self._stop.is_set():
            self._buffer_stream_op(("ref", {"object_ids": [oid],
                                            "ledger": None}))

    def cache_local(self, oid: str, wire: bytes) -> None:
        with self._local_lock:
            self._local_values[oid] = wire
            while len(self._local_values) > 4096:
                self._local_values.popitem(last=False)
            self._local_cv.notify_all()

    def _wake_local_waiters(self) -> None:
        """Channel-death hook: get() waiters parked on in-flight direct
        calls must re-check (and fall back to the authoritative GCS)."""
        with self._local_lock:
            self._local_cv.notify_all()

    # --------------------------------------------------------------- export
    def export_callable(self, obj: Any) -> str:
        # Per-object fn_id cache: re-pickling the function on EVERY submit
        # dominated the task hot path (sha1-of-cloudpickle per call).  The
        # reference pins a RemoteFunction's pickle at first submission —
        # later closure-cell mutations intentionally do not re-export.
        try:
            cached = self._fn_id_cache.get(obj)
        except TypeError:  # unhashable callable (rare)
            cached = None
        if cached is not None:
            return cached
        blob = dumps_call(obj)
        fn_id = hashlib.sha1(blob).hexdigest()[:16]
        if fn_id not in self._exported:
            self.rpc("export_function", fn_id=fn_id, blob=blob)
            self._exported.add(fn_id)
        try:
            self._fn_id_cache[obj] = fn_id
        except TypeError:
            pass
        return fn_id

    def fetch_callable(self, fn_id: str) -> Any:
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            resp = self.rpc("fetch_function", fn_id=fn_id)
            fn = loads_call(resp["blob"])
            self._fn_cache[fn_id] = fn
        return fn

    # ------------------------------------------------------- arg marshalling
    def _pack_args(self, args: tuple, kwargs: dict, batched: bool = False
                   ) -> Tuple[dict, List[str], List[str], List[str],
                              List[tuple]]:
        """Returns (fields, deps, borrows, transient_refs, pre_ops).
        ``batched``: the caller ships specs via the ordered submit batch,
        so big arg payloads become ("put", ...) pre-ops in that stream
        instead of a synchronous put round trip.

        Top-level ObjectRef args are passed by reference and resolved to
        values before execution (= deps).  Refs nested inside values stay
        refs (= borrows, pinned for the task's duration).  transient_refs
        are value-payload objects this call must release after the server
        has pinned them as deps (returned, not stored on self: concurrent
        submits from multiple threads must not release each other's refs).
        """
        layout = []
        values = []
        for a in args:
            if isinstance(a, ObjectRef):
                layout.append(("ref", str(a.id)))
            else:
                layout.append(("val", len(values)))
                values.append(a)
        klayout = {}
        for k, v in kwargs.items():
            if isinstance(v, ObjectRef):
                klayout[k] = ("ref", str(v.id))
            else:
                klayout[k] = ("val", len(values))
                values.append(v)
        if not values:
            # no by-value args: skip the serializer round trip entirely
            # (the no-arg task/actor-call hot path); _unpack_args yields
            # [] when values_blob is absent
            fields = {"arg_layout": layout, "kwarg_layout": klayout}
            deps = [oid for tag, oid in
                    [e for e in layout if e[0] == "ref"] +
                    [e for e in klayout.values() if e[0] == "ref"]]
            return fields, deps, [], [], []
        wire, refs = serialize_to_bytes(values)
        borrows = [str(r.id) for r in refs]
        deps = [oid for tag, oid in
                [e for e in layout if e[0] == "ref"] +
                [e for e in klayout.values() if e[0] == "ref"]]
        fields = {"arg_layout": layout, "kwarg_layout": klayout}
        transient: List[str] = []
        pre_ops: List[tuple] = []
        if len(wire) <= GLOBAL_CONFIG.inline_object_max_bytes:
            fields["values_blob"] = wire
        elif batched and not self.is_client:
            # big arg payloads ride the object plane, not the control
            # socket — single-copy: the already-serialized wire goes
            # straight to the slab/shm plane, and the put_object rides the
            # SAME ordered submit batch as the spec (transient=True: no
            # client ref to release later; the spec's dep pin — applied
            # later in the same batch — owns the lifetime).  The spec must
            # never overtake the put: a worker would park on the missing
            # arg, release its CPU, and the scheduler over-dispatches.
            oid = str(ObjectID.make(self.worker_id, KIND_PUT,
                                    self._put_seq()))
            loc = self._write_wire(oid, wire)
            pre_ops.append(("put", {
                "object_id": oid, "loc": loc, "size": len(wire),
                "contained": borrows, "transient": True,
                "node_id": self.node_id}))
            fields["values_ref"] = oid
            deps = deps + [oid]
        else:
            vref = self.put(values)
            fields["values_ref"] = str(vref.id)
            deps = deps + [str(vref.id)]
            vref._skip_release = True  # scheduler dep-hold takes over
            transient.append(str(vref.id))  # drop our ledger ref post-submit
        return fields, deps, borrows, transient, pre_ops

    def _unpack_args(self, spec: dict) -> Tuple[list, dict]:
        if "values_blob" in spec:
            values = deserialize_from(memoryview(spec["values_blob"]))
        elif "values_ref" in spec:
            # fast path: the arg payload was written to the same-host slab
            # by the submitter and is pinned by this task's dep — read it
            # directly, no get_meta round trip (the 100KB-arg hot loop)
            values = None
            slab = self.slab
            if slab is not None:
                wire = slab.get(spec["values_ref"])
                if wire is not None:
                    values = deserialize_from(memoryview(wire))
            if values is None:
                values = self.get_one(ObjectRef(spec["values_ref"],
                                                worker=self,
                                                skip_release=True))
        else:
            values = []
        ref_ids = [oid for tag, oid in spec["arg_layout"] if tag == "ref"] + \
                  [oid for tag, oid in spec["kwarg_layout"].values() if tag == "ref"]
        resolved = {}
        if ref_ids:
            vals = self.get([ObjectRef(o, worker=self, skip_release=True)
                             for o in ref_ids])
            resolved = dict(zip(ref_ids, vals))
        args = []
        for tag, v in spec["arg_layout"]:
            args.append(resolved[v] if tag == "ref" else values[v])
        kwargs = {}
        for k, (tag, v) in spec["kwarg_layout"].items():
            kwargs[k] = resolved[v] if tag == "ref" else values[v]
        return args, kwargs

    # ------------------------------------------------------------ submission
    def submit(self, fn: Any, args: tuple, kwargs: dict, *,
               num_returns: int = 1, num_cpus: float = 1,
               num_tpus: float = 0, resources: Optional[dict] = None,
               max_retries: Optional[int] = None, retry_exceptions: bool = False,
               scheduling_strategy: Any = None, name: Optional[str] = None,
               runtime_env: Optional[dict] = None) -> List[ObjectRef]:
        if runtime_env:
            from ray_tpu._private import runtime_env as renv
            runtime_env = renv.prepare(runtime_env, self)
        fn_id = self.export_callable(fn)
        fields, deps, borrows, transient, pre_ops = self._pack_args(
            args, kwargs, batched=True)
        task_id = TaskID.new()
        return_ids = [str(ObjectID.make(self.worker_id, KIND_RETURN, self._ret_seq()))
                      for _ in range(num_returns)]
        spec = {
            "task_id": task_id, "fn_id": fn_id,
            "name": name or getattr(fn, "__name__", "task"),
            "owner": self.worker_id,
            "return_ids": return_ids, "num_returns": num_returns,
            "deps": deps, "borrows": borrows,
            "num_cpus": num_cpus, "num_tpus": num_tpus,
            "resources": resources or {},
            "max_retries": (GLOBAL_CONFIG.task_default_max_retries
                            if max_retries is None else max_retries),
            "retry_exceptions": retry_exceptions,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": runtime_env,
            **fields,
        }
        span = tracing.current_span()
        if span is not None and span.sampled:
            # OTel-style propagation: the task's span will parent to this
            # one in the timeline dump (reference: ray.util.tracing).
            # Head-based sampling: a sampled-out root propagates nothing.
            spec["trace_ctx"] = span.to_dict()
        # one-way submit: return ids are generated client-side, so there is
        # nothing to wait for — pipelined submissions instead of a control-
        # plane round trip per task (reference: lease-cached submission).
        # Specs batch 64-to-a-message (r3: per-message framing was the
        # measured residual of the task hot loop); transient releases ride
        # the same batch AFTER their spec so the dep pin wins the race.
        self._buffer_submit(spec, transient, pre_ops)
        return [ObjectRef(oid, worker=self) for oid in return_ids]

    def _track_owned(self, spec: dict) -> None:
        with self._owned_lock:
            self._owned_specs[spec["task_id"]] = spec
            for oid in spec["return_ids"]:
                self._owned_by_ret[oid] = spec["task_id"]
            while len(self._owned_specs) > 100_000:
                _, old = self._owned_specs.popitem(last=False)
                for oid in old["return_ids"]:
                    self._owned_by_ret.pop(oid, None)

    def _untrack_owned_ret(self, oid: str) -> None:
        """A return was observed terminal (or its ref released): the task
        no longer needs owner-side lineage."""
        with self._owned_lock:
            tid = self._owned_by_ret.pop(oid, None)
            if tid is None:
                return
            spec = self._owned_specs.get(tid)
            if spec is not None and not any(
                    r in self._owned_by_ret for r in spec["return_ids"]):
                del self._owned_specs[tid]

    def _resubmit_owned(self, ch: protocol.RpcChannel) -> None:
        """Reconnected to a RESTARTED head: re-seal locally-held arg
        payloads (slab/shm segments survive the head) and resubmit every
        in-flight owned spec as one ordered batch."""
        with self._owned_lock:
            specs = [dict(s) for s in self._owned_specs.values()]
        if not specs:
            return
        logger.warning("head restart detected: resubmitting %d in-flight "
                       "owned tasks", len(specs))
        ops: List[tuple] = []
        sealed: set = set()
        for spec in specs:
            for k in [k for k in spec if k.startswith("_")]:
                spec.pop(k)
            for dep in list(spec.get("deps", ())):
                if dep in sealed or dep in self._owned_by_ret:
                    continue  # produced by another resubmitted task
                wire = None
                slab = self.slab
                if slab is not None:
                    wire = slab.get(dep)
                if wire is not None:
                    ops.append(("put", {"object_id": dep, "loc": "slab",
                                        "size": len(wire), "contained": [],
                                        "transient": True,
                                        "node_id": self.node_id}))
                    sealed.add(dep)
                elif os.path.exists(f"/dev/shm/rtpu_{dep}"):
                    ops.append(("put", {
                        "object_id": dep, "loc": "shm",
                        "size": os.path.getsize(f"/dev/shm/rtpu_{dep}"),
                        "contained": [], "transient": True,
                        "node_id": self.node_id}))
                    sealed.add(dep)
            ops.append(("spec", spec))
        ch.send_oneway("submit_batch", client_id=self.worker_id, ops=ops)

    def _buffer_submit(self, spec: dict, releases: List[str],
                       pre_ops: Optional[List[tuple]] = None) -> None:
        if not self.is_client:
            self._track_owned(spec)
        entries = list(pre_ops or ()) + [("spec", spec)] + \
            [("rel", o) for o in releases]
        if self.is_client:
            # proxied clients: no background flusher thread (their submit
            # rate never needed batching) — ship immediately
            self._send_submit_batch(entries)
            return
        full = False
        with self._submit_lock:
            self._submit_buf.extend(entries)
            if not self._submit_first:
                self._submit_first = time.monotonic()
            self._submit_pending.set()
            full = len(self._submit_buf) >= 64
            if not full:
                self._ensure_flusher_locked()
        if full:
            self._drain_submits()

    def _flush_submits(self) -> None:
        self._drain_pending_pins()
        self._drain_submits()

    def _buffer_stream_op(self, op: tuple) -> None:
        """Queue one op on the ordered submit stream (flushed within ~2ms
        or before any two-way RPC).  Pin/unpin pairs for the same object
        MUST both ride this stream: a pin that buffers while its release
        goes out directly (socket oneway or the in-process inline path)
        applies in the wrong order and frees the object under the pin —
        the free-before-pin race."""
        if self.is_client:
            self.rpc_oneway("submit_batch", ops=[op])
            return
        full = False
        with self._submit_lock:
            self._submit_buf.append(op)
            if not self._submit_first:
                self._submit_first = time.monotonic()
            self._submit_pending.set()
            full = len(self._submit_buf) >= 64
            if not full:
                self._ensure_flusher_locked()
        if full:
            self._drain_submits()

    def _buffer_ref_add(self, object_ids: List[str],
                        ledger: Optional[str] = None) -> None:
        """Pin refs for this client.  Explicit-ledger pins (in-flight
        actor args) ride the ordered submit stream unchanged.  Client-
        ledger pins (actor-call returns) are NETTED: they sit in
        _pending_pins where a release of the same oid cancels them
        outright; survivors are flushed onto the stream by the flusher.
        Only safe for refs whose SEAL is concurrent with the pin: the
        seal-with-zero-refs window (actor seals before the pin — or the
        netted pair never arrives at all) is covered by the GCS's 10s
        graceful-free grace.  Long-sealed objects (borrows) must use the
        prompt stream path instead — see notify_borrow."""
        if ledger is not None or self.is_client:
            # clients have no flusher thread: ship immediately
            self._buffer_stream_op(("ref", {"object_ids": object_ids,
                                            "ledger": ledger}))
            return
        with self._release_lock:
            for oid in object_ids:
                self._pending_pins[oid] = self._pending_pins.get(oid, 0) + 1
        # deliberately NO flusher wakeup: a pin only ADDS protection, and
        # the GCS rc-0-at-seal grace is 10s — the flusher's idle 1s tick
        # drains survivors.  Waking it per call would put a drain (and a
        # GCS lock acquisition) back on the hot loop netting removed.
        with self._submit_lock:
            self._ensure_flusher_locked()

    def _drain_pending_pins(self) -> None:
        """Move surviving netted pins onto the ordered submit stream
        (direct buffer append — must not recurse into a drain).  The
        pop-and-append is atomic under _release_lock: a concurrent
        release() of the same oid either nets against the pin (runs
        before the pop) or finds the pin already in _submit_buf and
        flushes it first (runs after) — it can never slip between and
        ship ahead of the pin.  Lock order release_lock → submit_lock;
        nothing takes them in the reverse order."""
        with self._release_lock:
            if not self._pending_pins:
                return
            pins, self._pending_pins = self._pending_pins, {}
            oids = [oid for oid, n in pins.items() for _ in range(n)]
            with self._submit_lock:
                self._submit_buf.append(("ref", {"object_ids": oids,
                                                 "ledger": None}))
                if not self._submit_first:
                    self._submit_first = time.monotonic()
                self._submit_pending.set()

    def _ensure_flusher_locked(self) -> None:
        # _submit_lock held
        if not self._submit_flusher_on and not self.is_client:
            self._submit_flusher_on = True
            threading.Thread(target=self._submit_flusher,
                             name="submit-flusher", daemon=True).start()

    def _drain_submits(self) -> None:
        """Pop the whole buffer and ship it; on a transient channel break
        REQUEUE it at the front.  The head is still alive (no epoch
        change), so _resubmit_owned never fires — dropping the batch would
        lose submissions whose .remote() already returned, hanging their
        get() forever.  rpc_oneway drops the dead shared channel on error
        (its break classes: OSError/ValueError/ConnectionError), so the
        retry (the flusher's next pass) re-dials.  pop→send is atomic
        under _submit_send_lock so concurrent drains can't reorder
        batches on the wire OR interleave requeues out of order."""
        with self._submit_send_lock:
            with self._submit_lock:
                if not self._submit_buf:
                    return
                flush, self._submit_buf = self._submit_buf, []
                self._submit_first = 0.0
            try:
                self._send_submit_batch(flush)
            except (OSError, ValueError, ConnectionError, EOFError):
                # EOFError: the negotiated re-dial (RpcChannel negotiate)
                # recv()s mid-hello — a half-restarted head can EOF there
                with self._submit_lock:
                    self._submit_buf[:0] = flush
                    if not self._submit_first:
                        self._submit_first = time.monotonic()
                    self._submit_pending.set()
                    # ensure someone retries even if the flusher was
                    # never started (all-exact-64-batch history)
                    self._ensure_flusher_locked()

    def _send_submit_batch(self, entries: List[Any]) -> None:
        # ordered op stream: ("put", msg) | ("spec", spec) | ("rel", oid) —
        # the server applies them in sequence, so an arg-payload put always
        # lands before the spec that deps on it, and a transient release
        # always lands after the spec whose dep pin replaces it
        self.rpc_oneway("submit_batch", ops=entries)

    def _submit_flusher(self) -> None:
        """Ships a lone buffered submit within ~2ms: fire-and-forget tasks
        must not wait for a 64-deep batch that may never fill.  Parks on
        an event while the buffer is empty (zero wakeups when idle)."""
        while not self._stop.is_set():
            if not self._submit_pending.wait(timeout=1.0):
                # idle tick: drain netted-pin survivors (refs the caller
                # kept) — their only deadline is the GCS's 10s grace
                with self._release_lock:
                    pins = bool(self._pending_pins)
                if pins:
                    self._flush_submits()
                continue
            time.sleep(0.0015)  # let a burst coalesce into one batch
            with self._submit_lock:
                due = bool(self._submit_buf)
                if not due:
                    # nothing left: park until the next buffered item.  A
                    # concurrent buffer-er re-sets the event AFTER
                    # inserting, so this clear can never strand work.
                    self._submit_pending.clear()
            if due:
                self._flush_submits()

    # ---------------------------------------------------------- actor client
    def create_actor(self, cls: Any, args: tuple, kwargs: dict, *,
                     num_cpus: float = 1, num_tpus: float = 0,
                     resources: Optional[dict] = None,
                     hold_resources: bool = True,
                     max_restarts: int = 0, max_task_retries: int = 0,
                     max_concurrency: int = 1, name: Optional[str] = None,
                     namespace: str = "default", detached: bool = False,
                     get_if_exists: bool = False,
                     scheduling_strategy: Any = None,
                     runtime_env: Optional[dict] = None) -> dict:
        if runtime_env:
            from ray_tpu._private import runtime_env as renv
            runtime_env = renv.prepare(runtime_env, self)
        class_blob_id = self.export_callable(cls)
        fields, deps, borrows, transient, _ = self._pack_args(args, kwargs)
        from ray_tpu._private.ids import ActorID
        actor_id = ActorID.new()
        task_id = TaskID.new()
        method_meta = {
            m: {"num_returns": getattr(getattr(cls, m), "__ray_num_returns__", 1)}
            for m in dir(cls) if callable(getattr(cls, m, None))
            and not m.startswith("__")
        }
        spec = {
            "task_id": task_id, "actor_id": actor_id,
            "is_actor_creation": True,
            "hold_resources": hold_resources,
            "class_blob_id": class_blob_id,
            "class_name": getattr(cls, "__name__", "Actor"),
            "name": name, "namespace": namespace, "detached": detached,
            "get_if_exists": get_if_exists,
            "owner": self.worker_id,
            "return_ids": [], "num_returns": 0,
            "deps": deps, "borrows": borrows,
            "num_cpus": num_cpus, "num_tpus": num_tpus,
            "resources": resources or {},
            "max_restarts": max_restarts, "max_task_retries": max_task_retries,
            "max_concurrency": max_concurrency,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": runtime_env,
            "method_meta": method_meta,
            **fields,
        }
        resp = self.rpc("create_actor", spec=spec)
        for oid in transient:
            self.rpc_oneway("release", object_id=oid)
        return {"actor_id": resp["actor_id"], "method_meta": method_meta,
                "existing": resp.get("existing", False)}

    def _actor_channel(self, actor_id: str, max_task_retries: int) -> "_ActorChannel":
        with self._actor_chan_lock:
            ch = self._actor_channels.get(actor_id)
            if ch is None or ch.closed:
                ch = _ActorChannel(self, actor_id, max_task_retries)
                self._actor_channels[actor_id] = ch
            return ch

    def call_actor(self, actor_id: str, method: str, args: tuple, kwargs: dict, *,
                   num_returns: int = 1, max_task_retries: int = 0) -> List[ObjectRef]:
        fields, deps, borrows, transient, _ = self._pack_args(args, kwargs)
        call_id = f"{self.worker_id}:{self._call_seq()}"
        return_ids = [str(ObjectID.make(self.worker_id, KIND_RETURN, self._ret_seq()))
                      for _ in range(num_returns)]
        # return-id pins ride the buffered stream (their release is the
        # client's own ObjectRef.__del__ → same stream, ordered).  Arg
        # pins must NOT buffer: the actor's release_all for this call's
        # ledger races ahead of a deferred flush on a fast method (no
        # cross-channel ordering) and would pop the ledger before the pin
        # lands, leaking the args forever.  Sent BEFORE the call, the pin
        # is always in flight ahead of the actor's completion.
        self._buffer_ref_add(return_ids)
        hold = deps + borrows
        if hold:
            self.rpc_oneway("add_refs", object_ids=hold,
                            ledger=f"call:{call_id}")
        span = tracing.current_span()
        msg = {"kind": "call", "call_id": call_id, "method": method,
               "return_ids": return_ids, "num_returns": num_returns,
               "_retries_left": max_task_retries,
               "trace_ctx": (span.to_dict()
                             if span is not None and span.sampled
                             else None),
               "arg_ledger": f"call:{call_id}" if hold else None, **fields}
        ch = self._actor_channel(actor_id, max_task_retries)
        with self._actor_chan_lock:
            for oid in return_ids:
                self._inflight_calls[oid] = (actor_id, call_id)
        ch.send_call(msg)
        for oid in transient:
            # MUST follow the arg-pin "ref" op in stream order — a direct
            # oneway here applies before the buffered pin and frees the
            # arg payload under it (free-before-pin)
            self._buffer_stream_op(("rel", oid))
        return [ObjectRef(oid, worker=self) for oid in return_ids]

    def _mark_call_done(self, oid: str) -> None:
        """A return object materialized: the actor call that produced it
        completed — clear it from in-flight bookkeeping so a later
        disconnect never resubmits it (double execution on a restarted
        stateful actor)."""
        with self._actor_chan_lock:
            entry = self._inflight_calls.pop(oid, None)
            if entry is None:
                return
            actor_id, call_id = entry
            ch = self._actor_channels.get(actor_id)
        if ch is not None:
            ch.mark_done(call_id)

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self.rpc("kill_actor", actor_id=actor_id, no_restart=no_restart)

    # -------------------------------------------------------------- shutdown
    def shutdown(self) -> None:
        self._flush_releases(all_threads=True)
        # _stop first: with it set, rpc() raises instead of entering the
        # 30s reconnect grace — a dead head must not stall shutdown for a
        # best-effort telemetry flush
        self._stop.set()
        self._final_metrics_flush()
        from ray_tpu._private import flight_recorder
        flight_recorder.record("shutdown", "clean worker shutdown")
        if self._local_server() is None:
            # pure worker/driver process: discharge the recorder mmap
            # now.  In a head==driver process the GCS still serves after
            # this worker closes — GcsServer.shutdown closes it (and
            # stops the shared sampler the same way).
            flight_recorder.close()
            from ray_tpu.util import profiler as profiler_mod
            profiler_mod.close()
        with self._actor_chan_lock:
            for ch in self._actor_channels.values():
                ch.close()
            self._actor_channels.clear()
        with self._raylet_ref_lock:
            if self._raylet_ref_conn is not None:
                try:
                    self._raylet_ref_conn.close()
                except OSError:
                    pass
                self._raylet_ref_conn = None
        self._data_pool.close_all()
        self.pool.close_all()

    # ====================================================== executor (worker)
    def _reattach_task_conn(self):
        """After a GCS crash: re-dial, re-register, re-attach the push
        channel, and re-announce a live actor.  Returns the new conn or
        None when the grace window expires (then the worker exits)."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        deadline = time.monotonic() + GLOBAL_CONFIG.gcs_reconnect_timeout_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                # order matters: register first (rebuilds WorkerState),
                # then attach the push conn, then re-announce the actor
                self._reconnect_pool()
                c = self.open_conn(self.gcs_path)
                c.send({"kind": "attach_task_conn",
                        "worker_id": self.worker_id,
                        "reattach": {
                            "pid": os.getpid(),
                            "node_id": self.node_id,
                            "tpu": os.environ.get("RTPU_TPU_WORKER") == "1",
                            # declared up front so the GCS never marks an
                            # actor worker "idle" (its main thread sits in
                            # serve_forever and can't run plain tasks)
                            "actor_id": (self._actor_announce or
                                         {}).get("actor_id"),
                        }})
                with self._task_conn_lock:
                    self._task_conn = c
                if self._actor_announce is not None:
                    self._send_event({"kind": "actor_ready",
                                      "reattach": True,
                                      **self._actor_announce})
                self._open_ctl_conn()  # idempotent: the ctl thread
                # re-dials on its own; this only covers a never-started one
                logger.info("reattached task conn after GCS restart")
                return c
            except (EOFError, OSError, ConnectionError):
                time.sleep(0.5)
        return None

    def _dial_task_endpoint(self):
        """The push channel's server: the node's local raylet when one
        advertises (RTPU_RAYLET_SOCK — a unix dial even for otherwise
        proxied remote workers), the GCS otherwise."""
        if self.raylet_sock is not None:
            return protocol.connect(self.raylet_sock)
        return self.open_conn(self.gcs_path)

    def run_worker_loop(self) -> None:
        """Main loop of a spawned worker process.

        Tasks execute directly on THIS thread, straight off the task-conn
        recv — no reader→executor queue handoff (two scheduler wakeups per
        task on small hosts, ~100-200µs measured).  Out-of-band control
        (cancel / drop_queued / dump_stack / stop_worker) rides a second
        ``ctl`` connection whose dedicated reader thread stays responsive
        while a task runs; the same kinds are still honored here when they
        arrive on the task conn (ctl-attach race fallback)."""
        conn = self._dial_task_endpoint()
        conn.send({"kind": "attach_task_conn", "worker_id": self.worker_id})
        with self._task_conn_lock:
            self._task_conn = conn
        self._open_ctl_conn()
        self._exec_thread_id = threading.get_ident()
        from collections import deque as _deque

        from ray_tpu._private import flight_recorder
        lookahead: "_deque" = _deque()  # frames pre-read by the OOB drain
        while not self._stop.is_set():
            if lookahead:
                msg = lookahead.popleft()
            else:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    if self._stop.is_set():
                        break
                    if self.raylet_sock is not None:
                        # raylet gone = this node is being torn down (a
                        # dead raylet never restarts in place; a HEAD
                        # restart doesn't touch this local conn — the
                        # raylet heals upstream on its own).  Exit; the
                        # agent's pool loop forks replacements.
                        self._stop.set()
                        break
                    # head gone: outlive it and reattach (GCS fault
                    # tolerance) — actors keep serving direct calls the
                    # whole time; only the control-plane link heals.
                    conn = self._reattach_task_conn()
                    if conn is None:
                        self._stop.set()
                        break
                    continue
            kind = msg.get("kind")
            if flight_recorder.enabled():
                # execute_task receipt is recorded by _execute_task's
                # "exec" record itself (task id included) — recording
                # the frame too would double the hot path's record cost
                # for no extra forensics
                if kind != "execute_task":
                    flight_recorder.record("task_frame", str(kind))
            if kind == "execute_task":
                dseq = msg.get("dseq")
                self._execute_task(msg["spec"])
                # prepushed lease-inheriting batch (one dispatch message
                # carries the worker's whole pipeline): run back-to-back
                for spec in msg.get("queued", ()):
                    if self._stop.is_set():
                        break
                    if self._ctl_down:
                        # ctl channel unavailable: OOB frames (e.g. a
                        # drop_queued revoking THESE prepushed specs after
                        # a blocked-worker reclaim) fell back to this conn
                        # — service them before running the next spec, or
                        # a reclaimed spec also re-dispatched elsewhere
                        # would double-execute
                        self._drain_task_conn_oob(conn, lookahead)
                    if (spec["task_id"], dseq) in self._dropped_ids:
                        self._dropped_ids.pop((spec["task_id"], dseq), None)
                        continue
                    self._execute_task(spec)
            elif kind == "create_actor":
                if self._become_actor(msg["spec"]):
                    break  # serve_forever returned: the actor exited
                # creation failed: the GCS returns this worker to the
                # idle pool — keep serving plain tasks on this conn
            else:
                self._handle_oob(msg)
        self._final_metrics_flush()
        sys.exit(0)

    def _actor_conn_monitor(self) -> None:
        """Task-conn reader for ACTOR workers: the main thread parks in
        serve_forever, so this thread owns the control-plane link —
        notices head death (EOF → reattach + re-announce) and handles
        OOB kinds arriving on the task conn."""
        with self._task_conn_lock:
            conn = self._task_conn
        while not self._stop.is_set() and conn is not None:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                if self._stop.is_set():
                    return
                if self.raylet_sock is not None:
                    # node teardown (see run_worker_loop): stop serving
                    self._stop.set()
                    return
                conn = self._reattach_task_conn()
                if conn is None:
                    self._stop.set()
                    return
                continue
            try:
                self._handle_oob(msg)
            except Exception:  # noqa: BLE001 - monitor must keep serving
                logger.exception("actor conn message failed")

    def _drain_task_conn_oob(self, conn, lookahead) -> None:
        """Read any frames already queued on the task conn, handling OOB
        kinds inline and deferring work frames to ``lookahead`` (only
        used while the ctl channel is down — its fallback frames land
        here and must not wait behind a prepush batch)."""
        try:
            while conn.poll(0):
                m = conn.recv()
                if m.get("kind") in ("execute_task", "create_actor"):
                    lookahead.append(m)
                else:
                    self._handle_oob(m)
        except (OSError, EOFError):
            pass  # conn death is the main loop's recv to notice

    def _handle_oob(self, msg: dict) -> None:
        """Out-of-band control kinds (normally via the ctl conn; also
        honored on the task conn while idle)."""
        kind = msg.get("kind")
        if kind == "cancel":
            self._cancel_current(msg["task_id"])
        elif kind == "drop_queued":
            # the GCS revoked prepushed specs this worker holds but
            # hasn't started (pipeline reclaim, or cancel of a queued
            # spec).  Revocations are scoped by the DISPATCH sequence the
            # copy arrived under: a stale drop (the copy already ran
            # before the revocation landed) can then never poison a later
            # legitimate re-dispatch of the same task id to this worker.
            for t, d in msg["pairs"]:
                self._dropped_ids[(t, d)] = None
            while len(self._dropped_ids) > 1024:
                self._dropped_ids.popitem(last=False)
        elif kind == "dump_stack":
            # `ray_tpu stack` (reference: py-spy attach): dump all
            # threads — works mid-task and inside actors (ctl thread)
            self._send_event({"kind": "stack_dump",
                              "text": _dump_all_stacks()})
        elif kind == "stop_worker":
            self._stop.set()
            srv = getattr(self, "_actor_server", None)
            if srv is not None:
                # actor worker: the main thread parks in serve_forever —
                # stop the server (mechanics only; the control plane
                # already holds the death reason + restart policy) so
                # the process actually exits and direct callers fail
                # over to the restarted incarnation
                srv.stop_serving()

    def _open_ctl_conn(self) -> None:
        """Start the out-of-band control channel thread (idempotent).
        The thread owns dialing AND re-dialing: a ctl-only connection
        failure must not permanently degrade mid-task cancel/stop to
        between-task delivery (the task conn stays the liveness signal;
        ctl is best-effort but self-healing)."""
        if getattr(self, "_ctl_thread_on", False):
            return
        self._ctl_thread_on = True
        threading.Thread(target=self._ctl_loop,
                         name="worker-ctl", daemon=True).start()

    def _ctl_loop(self) -> None:
        conn = None
        backoff = 0.5
        while not self._stop.is_set():
            if conn is None:
                try:
                    conn = self._dial_task_endpoint()
                    conn.send({"kind": "attach_worker_ctl",
                               "worker_id": self.worker_id})
                    self._ctl_down = False
                    backoff = 0.5
                except (OSError, EOFError, ConnectionError):
                    conn = None
                    self._ctl_down = True
                    if self._stop.wait(backoff):
                        return
                    backoff = min(10.0, backoff * 2)
                    continue
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None  # head restarting / conn broke: re-dial
                self._ctl_down = True
                if self._stop.wait(0.5):
                    return
                continue
            try:
                self._handle_oob(msg)
                if msg.get("kind") == "stop_worker":
                    # the main thread is parked in task-conn recv (or an
                    # actor's serve_forever): shut the task conn down so
                    # its recv raises and the loop observes _stop
                    with self._task_conn_lock:
                        if self._task_conn is not None:
                            protocol.shutdown_conn(self._task_conn)
                    return
            except Exception:  # noqa: BLE001 - control must keep serving
                logger.exception("ctl message failed: %s", msg.get("kind"))

    def _cancel_current(self, task_id: str) -> None:
        spec = self._current_spec
        if spec is not None and spec.get("task_id") == task_id \
                and self._exec_thread_id is not None:
            import ctypes
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(self._exec_thread_id),
                ctypes.py_object(exc.TaskCancelledError))

    def _serialize_result(self, value: Any) -> dict:
        from ray_tpu._private.serialization import (serialize,
                                                    serialized_size,
                                                    to_wire_bytes)
        pickled, buffers, refs = serialize(value)
        size = serialized_size(pickled, buffers)
        contained = [str(r.id) for r in refs]
        if self.is_client:
            # no local data plane: small results inline on the control
            # plane; large ones spool locally (writev, no full-wire
            # staging copy) or stream to the head's store in chunks
            if size <= GLOBAL_CONFIG.transfer_chunk_bytes:
                return {"loc": "inline", "data": to_wire_bytes(pickled, buffers),
                        "size": size, "contained": contained}
            return {"loc": "upload", "parts": (pickled, buffers),
                    "size": size, "contained": contained}
        if size <= GLOBAL_CONFIG.inline_object_max_bytes:
            return {"loc": "inline", "data": to_wire_bytes(pickled, buffers),
                    "size": size, "contained": contained}
        # large: straight to the data plane, serialized in _store_results
        # (slab for mid-size, single-copy writev segment for big)
        return {"loc": "shm", "parts": (pickled, buffers), "size": size,
                "contained": contained}

    def _store_results(self, return_ids: List[str], value: Any,
                       num_returns: int) -> List[dict]:
        if num_returns == 0:
            return []
        values = [value] if num_returns == 1 else list(value)
        if num_returns > 1 and len(values) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(values)} values")
        out = []
        for oid, v in zip(return_ids, values):
            res = self._serialize_result(v)
            if res["loc"] == "shm":
                pickled, buffers = res.pop("parts")
                slab = self.slab
                if slab is not None and \
                        res["size"] <= GLOBAL_CONFIG.slab_object_max_bytes:
                    from ray_tpu._private.serialization import to_wire_bytes
                    res["loc"] = self._write_wire(
                        oid, to_wire_bytes(pickled, buffers), overwrite=True)
                else:
                    shm_write_value(oid, pickled, buffers, overwrite=True)
            elif res["loc"] == "upload":
                pickled, buffers = res.pop("parts")
                res["loc"] = self._spool_or_upload(oid, pickled, buffers)
            out.append(res)
        return out

    def _spool_or_upload(self, oid: str, pickled, buffers) -> str:
        """Large bytes leaving a proxied worker: spool on THIS host's P2P
        data plane when an agent provides one (consumers pull from the
        holder directly; head relays only as fallback) — else stream to
        the head's store in chunks.  Returns the sealed loc.

        The spool write rides ``write_value_to_fd``'s writev path: the
        pickle head and out-of-band buffers stream straight from their
        backing memory into the spool file — the full wire bytes are
        never materialized in this process's heap.

        NOTE: remote-spooled objects currently do not survive a HEAD
        restart — agents exit on head loss (liveness watch), taking their
        spools with them; the GCS snapshot therefore indexes only
        head-local shm objects.  Agent reconnect (and with it spool
        survival) is the follow-on."""
        spool = os.environ.get("RTPU_SPOOL_DIR")
        if spool:
            data_plane.write_spool_value(spool, oid, pickled, buffers)
            return "remote"
        from ray_tpu._private.serialization import to_wire_bytes
        self._upload_wire(oid, to_wire_bytes(pickled, buffers))
        return "shm"  # now lives in the head's tmpfs plane

    def _upload_wire(self, oid: str, wire: bytes) -> None:
        """Stream large wire bytes to the head's store in chunks (the
        outbound half of cross-host transfer — reference: ObjectManager
        push; SURVEY.md §5.8 object plane)."""
        chunk = GLOBAL_CONFIG.transfer_chunk_bytes
        mv = memoryview(wire)
        total = len(wire)
        off = 0
        while True:
            piece = bytes(mv[off:off + chunk])
            resp = self.rpc("put_chunk", object_id=oid, offset=off,
                            total=total, data=piece)
            off += len(piece)
            if off >= total:
                if not resp.get("done"):
                    raise RuntimeError(f"chunked upload of {oid} incomplete")
                return

    def _apply_runtime_env(self, spec: dict):
        from ray_tpu._private import runtime_env as renv
        return renv.apply(spec.get("runtime_env"), self)

    def _restore_runtime_env(self, saved: dict) -> None:
        from ray_tpu._private import runtime_env as renv
        renv.restore(saved)

    def _execute_task(self, spec: dict) -> None:
        t0 = time.time()          # wall clock: timeline events
        t0m = time.monotonic()    # monotonic: latency metric (NTP-safe)
        self._current_spec = spec
        self.ctx.in_task = True
        self.ctx.task_id = spec["task_id"]
        from ray_tpu._private import flight_recorder
        if flight_recorder.enabled():
            flight_recorder.record(
                "exec", f"{spec.get('name', 'task')} "
                        f"{spec['task_id'][:16]}")
        done: dict = {}  # terminal frame (for the flight record below)
        parent_span = tracing.SpanContext.from_dict(spec.get("trace_ctx"))
        task_span = None
        if parent_span is not None:
            task_span = tracing.SpanContext(
                parent_span.trace_id, tracing._new_id(),
                parent_span.span_id, spec.get("name", "task"))
            tracing._set_span(task_span)
        saved_env = {}
        try:
            # inside the try: a bad runtime_env (missing KV blob, corrupt
            # zip) must fail THIS task, not kill the pooled worker process
            saved_env = self._apply_runtime_env(spec)
            fn = self.fetch_callable(spec["fn_id"])
            args, kwargs = self._unpack_args(spec)
            container = (spec.get("runtime_env") or {}).get("container")
            if container:
                # per-task exec prefix: the body runs inside the image
                # (reference: container runtime-env plugin)
                from ray_tpu._private import runtime_env as renv
                value = renv.run_in_container(container, fn, args, kwargs,
                                              self)
            else:
                value = fn(*args, **kwargs)
            results = self._store_results(spec["return_ids"], value,
                                          spec["num_returns"])
            done = {"kind": "task_done", "task_id": spec["task_id"],
                    "status": "ok", "results": results}
            self._attach_timeline_event(done, spec, t0, task_span)
            self._send_event(done)
        except Exception as e:  # noqa: BLE001
            err = e if isinstance(e, exc.RayTaskError) else \
                exc.RayTaskError.from_exception(spec.get("name", "task"), e)
            done = {"kind": "task_done", "task_id": spec["task_id"],
                    "status": "app_error",
                    "error": serialize_to_bytes(err)[0]}
            self._attach_timeline_event(done, spec, t0, task_span)
            self._send_event(done)
        finally:
            if flight_recorder.enabled():
                flight_recorder.record(
                    "task_done", f"{spec['task_id'][:16]} "
                                 f"{done.get('status', '?')}")
            self._restore_runtime_env(saved_env)
            self._current_spec = None
            self.ctx.in_task = False
            self.ctx.task_id = None
            if task_span is not None:
                tracing._set_span(None)
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_task_exec_seconds").observe(
                    time.monotonic() - t0m,
                    tags={"name": spec.get("name", "task")})

    def _attach_timeline_event(self, done_msg: dict, spec: dict, t0: float,
                               task_span) -> None:
        """Timeline profile event riding the task_done frame: one message
        per task instead of two (the separate profile_events oneway was a
        measured per-task head wakeup + handler on the serial hot path)."""
        if not GLOBAL_CONFIG.timeline_enabled:
            return
        ev = {"name": spec.get("name", "task"), "cat": "task",
              "ph": "X", "pid": self.node_id, "tid": self._pid,
              "ts": t0 * 1e6, "dur": (time.time() - t0) * 1e6}
        if task_span is not None:
            ev["args"] = task_span.to_dict()
        done_msg["events"] = [ev]

    # ------------------------------------------------------------ actor side
    def _become_actor(self, spec: dict) -> bool:
        """Instantiate the actor and serve its method calls.  Returns True
        when the actor served and exited (worker process is done), False
        when CREATION failed — the GCS puts this worker back in the idle
        pool, so the caller must return to the plain task loop."""
        from ray_tpu._private.actor_server import ActorServer
        self._current_spec = spec
        try:
            if (spec.get("runtime_env") or {}).get("container"):
                raise exc.RayTpuError(
                    "runtime_env['container'] applies to tasks; "
                    "containerized actors are not supported")
            # actor-lifetime runtime env (never restored: process is dedicated)
            self._apply_runtime_env(spec)
            cls = self.fetch_callable(spec["class_blob_id"])
            args, kwargs = self._unpack_args(spec)
            instance = cls(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            err = exc.RayTaskError.from_exception(
                spec.get("class_name", "Actor") + ".__init__", e)
            self._send_event({"kind": "actor_ready", "actor_id": spec["actor_id"],
                              "status": "error",
                              "error": serialize_to_bytes(err)[0]})
            self._current_spec = None
            return False
        self._current_spec = None
        server = ActorServer(self, spec, instance)
        # stop_worker must be able to stop the serve loop too: a
        # proc-less (remote/raylet) actor worker has no head-side pid
        # to signal, so ray_tpu.kill reaches it as an OOB ctl frame
        self._actor_server = server
        # kept for GCS-restart reattach: the actor re-announces itself to
        # a fresh head with the same id + addr (state intact)
        self._actor_announce = {"actor_id": spec["actor_id"],
                                "status": "ok", "addr": server.addr}
        self._send_event({"kind": "actor_ready", "actor_id": spec["actor_id"],
                          "status": "ok", "addr": server.addr})
        # the main thread parks in serve_forever below: hand the task conn
        # to a monitor thread (head-death reattach, OOB fallback)
        threading.Thread(target=self._actor_conn_monitor,
                         name="actor-conn-monitor", daemon=True).start()
        server.serve_forever()  # returns on exit_actor / stop
        self._stop.set()
        return True


class _ActorChannel:
    """Caller-side direct connection to one actor (pipelined, ordered)."""

    def __init__(self, worker: Worker, actor_id: str, max_task_retries: int):
        self.worker = worker
        self.actor_id = actor_id
        self.max_task_retries = max_task_retries
        self.closed = False
        self._lock = threading.Lock()
        # Sends serialize on their OWN lock: _read_loop must never park
        # behind a blocked conn.send while holding up reply draining.
        # With the actor executing serial calls on its connection-reader
        # thread (actor_server direct-exec), the actor stops recv'ing
        # during a method — if the caller ALSO stopped draining replies
        # (reader parked on the state lock a blocked sender holds), a
        # pipelined burst of ~100KB inline args/results could fill both
        # socket buffers and deadlock all three parties.  The caller
        # draining unconditionally breaks every such cycle: the actor's
        # reply send always completes, so its reader always resumes.
        self._send_lock = threading.Lock()
        self._outstanding: Dict[str, dict] = {}
        self._conn = None
        self._incarnation = -1
        self._connect(timeout=GLOBAL_CONFIG.actor_connect_timeout_s)

    def _connect(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while True:
            info = self.worker.rpc("get_actor_info", actor_id=self.actor_id,
                                   timeout=max(0.1, deadline - time.monotonic()))
            if info["state"] == "ALIVE":
                try:
                    self._conn = self.worker.open_conn(info["addr"])
                    break
                except (OSError, ConnectionError):
                    # stale address: the actor died but the control plane
                    # hasn't flipped its state yet — keep polling until
                    # RESTARTING/DEAD shows up or the deadline passes
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
                    continue
            if info["state"] == "DEAD":
                cerr = info.get("creation_error")
                if cerr is not None:
                    raise deserialize_from(memoryview(cerr))
                raise exc.RayActorError(self.actor_id,
                                        info.get("death_reason") or "actor died")
            if time.monotonic() > deadline:
                raise exc.GetTimeoutError(
                    f"actor {self.actor_id} not ready after {timeout}s")
            time.sleep(0.05)
        self._incarnation = info["incarnation"]
        threading.Thread(target=self._read_loop, args=(self._conn,),
                         name=f"actor-ch-{self.actor_id[:6]}", daemon=True).start()

    def mark_done(self, call_id: str) -> None:
        """The call's result was observed via the authoritative store —
        it must never be resubmitted."""
        with self._lock:
            self._outstanding.pop(call_id, None)

    def send_call(self, msg: dict) -> None:
        with self._lock:
            if self.closed:
                raise exc.RayActorError(self.actor_id, "channel closed")
            self._outstanding[msg["call_id"]] = msg
            conn = self._conn
        # the possibly-blocking socket write happens OUTSIDE the state
        # lock (see _send_lock comment in __init__); registered-but-
        # unsent calls are safe — a channel break resubmits outstanding
        with self._send_lock:
            try:
                conn.send(msg)
            except (OSError, ValueError):
                # reconnect path handles resubmission via _read_loop EOF
                pass

    def _read_loop(self, conn) -> None:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError, TypeError):
                # TypeError: close() from another thread nulls the handle
                # mid-recv — same meaning as EOF here
                break
            call_id = msg.get("call_id")
            with self._lock:
                self._outstanding.pop(call_id, None)
            # cache BEFORE clearing in-flight state: a get() parked on the
            # inline fast path (_await_inline_results) re-checks the cache
            # first and must find the value the moment it wakes
            for oid, res in zip(msg["return_ids"], msg.get("inline_results") or []):
                if res is not None:
                    self.worker.cache_local(oid, res)
            with self.worker._actor_chan_lock:
                for oid in msg["return_ids"]:
                    self.worker._inflight_calls.pop(oid, None)
            # non-inline (big) results: wake parked getters so they fall
            # through to the authoritative GCS path
            self.worker._wake_local_waiters()
        self._on_disconnect()

    def _on_disconnect(self) -> None:
        with self._lock:
            if self.closed:
                return
            pending = dict(self._outstanding)
            self._outstanding.clear()
        if not pending:
            with self._lock:
                self.closed = True
            return
        # The inline reply and the death can race: the actor seals results
        # with the GCS (authoritative) BEFORE replying, so a call whose
        # returns are already sealed COMPLETED — resubmitting it would
        # re-execute a finished method (observable with stateful actors).
        # Drop those from the pending set before applying retry budgets.
        done: set = set()
        try:
            oids = {oid: cid for cid, m in pending.items()
                    for oid in m["return_ids"]}
            metas = self.worker.rpc("peek_meta",
                                    object_ids=list(oids)).get("metas", {})
            sealed = {oid for oid, meta in metas.items()
                      if meta and meta.get("state") in ("ready", "error")}
            for cid, m in pending.items():
                if all(oid in sealed for oid in m["return_ids"]):
                    done.add(cid)
        except Exception:  # noqa: BLE001 - GCS unreachable: fall through
            pass           # to the retry budget (at-least-once)
        # actor died with calls in flight: per-call retry budget decides
        # resubmission vs sealing an error (reference: max_task_retries)
        resubmit, fail = {}, {}
        for call_id, msg in pending.items():
            if call_id in done:
                continue
            left = msg.get("_retries_left", 0)
            if left != 0:
                msg["_retries_left"] = left - 1 if left > 0 else -1
                msg["_resubmitted"] = True  # receiver re-checks the seal
                resubmit[call_id] = msg
            else:
                fail[call_id] = msg
        if resubmit:
            try:
                self._connect(timeout=60.0)
                with self._lock:
                    for call_id, msg in resubmit.items():
                        self._outstanding[call_id] = msg
                        try:
                            self._conn.send(msg)
                        except (OSError, ValueError):
                            break
            except (exc.RayTpuError, OSError) as e:
                fail.update(resubmit)
                with self._lock:
                    self.closed = True
        if fail:
            err_wire = serialize_to_bytes(
                exc.RayActorError(self.actor_id,
                                  "actor died with calls in flight"))[0]
            oids = [oid for msg in fail.values() for oid in msg["return_ids"]]
            try:
                self.worker.rpc("seal_errors", object_ids=oids, error=err_wire)
            except Exception:  # noqa: BLE001 - gcs also going down
                pass
        if not resubmit:
            with self._lock:
                self.closed = True
        # parked inline-fast-path getters must re-check channel liveness
        # (a closed channel routes them to the authoritative GCS path)
        self.worker._wake_local_waiters()

    def close(self) -> None:
        with self._lock:
            self.closed = True
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
        self.worker._wake_local_waiters()
