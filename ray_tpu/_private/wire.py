"""Versioned wire framing + a language-neutral control-message codec.

Reference analog: ``src/ray/protobuf/*.proto`` + ``src/ray/rpc/`` — the
reference's control plane is schema'd protobuf over gRPC, so any language
can speak it and versions negotiate at the channel level.  r3's wire format
was raw pickled dicts: single-language, unversioned, and every decode was a
``pickle.loads`` of peer-supplied bytes (HMAC-gated, but still the widest
possible parser).  This module closes that L0 gap (VERDICT r3 missing #3):

**Frame format.**  Every framed message is ``[version u8][codec u8][body]``
sent via ``Connection.send_bytes``.  Version bytes are 1..127 — a raw
pickle stream always begins with the PROTO opcode ``0x80``, so legacy
(pre-framing, version-0) peers are detected by the first byte and decoded
transparently: framed and legacy senders interoperate on one socket.

**Codecs.**  ``codec=1`` is *rtmsg*, a ~100-line tagged binary format for
the JSON-plus-bytes subset control messages actually use (None/bool/int/
float/str/bytes/list/tuple/dict).  Decoding rtmsg executes no code — unlike
pickle — and the format is demonstrably implementable in any language: the
C client ``native/src/rtmsg_client.c`` speaks it against a live head
(tests/test_polyglot_client.py), and ``native/src/wirecodec.c`` implements
it as a CPython extension at 2.2µs/frame — faster than C pickle — so with
the native build present EVERY encodable frame rides rtmsg, hot kinds
included.  ``codec=0`` is pickle, the per-frame fallback for genuinely
Python payloads (task arg objects, exceptions) and the no-toolchain path.

**Negotiation.**  A client opens at version 0 (legacy), sends a
``__proto_hello__`` RPC advertising ``[PROTO_MIN..PROTO_MAX]``; the server
answers with the highest common version (its own ceiling capped by the
client's) or rejects when the client's ceiling is below the server's
configured floor (``proto_min_version``).  Tested both ways in
tests/test_protocol_versioning.py.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Tuple

PROTO_MIN = 1   # framed, pickle codec only
PROTO_TRACE = 3  # understands the optional TRACE_FIELD on any frame
PROTO_RAYLET = 4  # speaks the raylet lease kinds (RAYLET_KINDS below)
PROTO_REPL = 5  # speaks the GCS replication kinds (REPL_KINDS below)
PROTO_MAX = 5   # framed, rtmsg + pickle + trace + raylet + replication
_PICKLE_OPCODE = 0x80  # first byte of every pickle protocol>=2 stream

# Optional span-context frame field (Dapper-style wire propagation):
# ``msg[TRACE_FIELD] = [trace_id, span_id]`` — attached ONLY on
# connections that negotiated >= PROTO_TRACE (control plane) or
# >= DATA_PROTO_TRACE (data plane), so un-upgraded peers see
# byte-identical frames.  The single writer/reader of this field is
# ray_tpu/util/tracing.py (attach_wire_trace / extract_wire_trace);
# rtlint's wire-trace rule rejects ad-hoc plumbing of the key.
TRACE_FIELD = "trace"

_CODEC_PICKLE = 0
_CODEC_RTMSG = 1

# ----------------------------------------------------------------- rtmsg
# Tag table (one byte each; lengths/counts are big-endian u32, ints are
# big-endian signed 64-bit, floats are IEEE-754 doubles):
#   0x01 None | 0x02 False | 0x03 True
#   0x10 int64 | 0x11 float64
#   0x20 str(u32 len, utf-8) | 0x21 bytes(u32 len)
#   0x30 list(u32 count) | 0x31 tuple(u32 count) | 0x32 dict(u32 count)
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
_pack_i64 = struct.Struct(">q").pack
_pack_f64 = struct.Struct(">d").pack
_pack_u32 = struct.Struct(">I").pack
_unpack_i64 = struct.Struct(">q").unpack_from
_unpack_f64 = struct.Struct(">d").unpack_from
_unpack_u32 = struct.Struct(">I").unpack_from


class WireError(ValueError):
    """Malformed or unsupported frame."""


class ProtocolVersionError(WireError):
    """Peer speaks a version outside our supported range."""


def _rtmsg_encode_into(buf: bytearray, obj: Any) -> None:
    # bool before int: isinstance(True, int)
    if obj is None:
        buf.append(0x01)
    elif obj is False:
        buf.append(0x02)
    elif obj is True:
        buf.append(0x03)
    elif type(obj) is int:
        if not _I64_MIN <= obj <= _I64_MAX:
            raise TypeError("int out of i64 range")
        buf.append(0x10)
        buf += _pack_i64(obj)
    elif type(obj) is float:
        buf.append(0x11)
        buf += _pack_f64(obj)
    elif type(obj) is str:
        raw = obj.encode("utf-8")
        buf.append(0x20)
        buf += _pack_u32(len(raw))
        buf += raw
    elif type(obj) in (bytes, bytearray, memoryview):
        # buffer widening: bytearray/memoryview encode as the bytes tag
        # and DECODE as bytes — fine for wire payloads (out-of-band
        # buffers, inline object data), where only content round-trips.
        # memoryview len() counts ELEMENTS, not bytes: cast to a flat
        # byte view first (non-contiguous views raise TypeError and fall
        # to the caller's pickle fallback, same as other unencodables).
        if type(obj) is memoryview:
            obj = obj.cast("B")
        buf.append(0x21)
        buf += _pack_u32(len(obj))
        buf += obj
    elif type(obj) is list:
        buf.append(0x30)
        buf += _pack_u32(len(obj))
        for v in obj:
            _rtmsg_encode_into(buf, v)
    elif type(obj) is tuple:
        buf.append(0x31)
        buf += _pack_u32(len(obj))
        for v in obj:
            _rtmsg_encode_into(buf, v)
    elif type(obj) is dict:
        buf.append(0x32)
        buf += _pack_u32(len(obj))
        for k, v in obj.items():
            _rtmsg_encode_into(buf, k)
            _rtmsg_encode_into(buf, v)
    else:
        # subclasses (numpy scalars, IntEnum, namedtuples) intentionally
        # land here: their identity would not round-trip
        raise TypeError(f"not rtmsg-encodable: {type(obj)!r}")


def _rtmsg_decode_from(buf, off: int) -> Tuple[Any, int]:
    tag = buf[off]
    off += 1
    if tag == 0x01:
        return None, off
    if tag == 0x02:
        return False, off
    if tag == 0x03:
        return True, off
    if tag == 0x10:
        return _unpack_i64(buf, off)[0], off + 8
    if tag == 0x11:
        return _unpack_f64(buf, off)[0], off + 8
    if tag == 0x20:
        n = _unpack_u32(buf, off)[0]
        off += 4
        return str(buf[off:off + n], "utf-8"), off + n
    if tag == 0x21:
        n = _unpack_u32(buf, off)[0]
        off += 4
        return bytes(buf[off:off + n]), off + n
    if tag in (0x30, 0x31):
        n = _unpack_u32(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _rtmsg_decode_from(buf, off)
            items.append(v)
        return (tuple(items) if tag == 0x31 else items), off
    if tag == 0x32:
        n = _unpack_u32(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _rtmsg_decode_from(buf, off)
            v, off = _rtmsg_decode_from(buf, off)
            d[k] = v
        return d, off
    raise WireError(f"bad rtmsg tag 0x{tag:02x} at {off - 1}")


def rtmsg_dumps(obj: Any) -> bytes:
    buf = bytearray()
    _rtmsg_encode_into(buf, obj)
    return bytes(buf)


def rtmsg_loads(raw: bytes) -> Any:
    obj, off = _rtmsg_decode_from(memoryview(raw), 0)
    if off != len(raw):
        raise WireError(f"trailing bytes after rtmsg value ({len(raw)-off})")
    return obj


# ----------------------------------------------------------------- frames
# Codec selection at v2 (measured, this host):
#   C rtmsg (native/src/wirecodec.c)  ~2.2µs/frame roundtrip
#   C pickle                          ~4.4µs
#   pure-Python rtmsg                 ~31µs
# With the native codec built (the normal case — gcc is in the image and
# the build caches), EVERY encodable frame rides rtmsg: fastest AND
# language-neutral, so hot kinds need no pickle carve-out.  Without it,
# the µs-critical kinds below stay on pickle and only non-hot control
# messages pay the pure-Python encoder; a polyglot peer can still
# negotiate v2 and read every non-payload control kind as rtmsg either
# way (BASELINE #7 latency contract unchanged).
_HOT_KINDS = frozenset({
    "submit_batch", "submit_task", "get_meta", "peek_meta", "wait",
    "add_ref", "add_refs", "release", "release_batch", "release_all",
    "task_done", "call", "put_object", "put_chunk", "fetch_chunk"})

# Refcount-plane oneway kinds: the GCS coalesces consecutive frames of
# these per connection and applies them in one batched lock acquisition
# (stream order preserved).  Declared here, next to the frame schema,
# because it is a wire-level contract: anything added must stay a pure
# refcount mutation with no reply and no cross-table side effects.
# One kind per line: tools/rtlint's wire pass anchors its findings (and
# their waivers) to the declaring line.
REF_KINDS = frozenset({
    # single-ref alias kept for minimal polyglot peers; the in-tree
    # Python client batches via add_refs, so no producer exists here
    # rtlint: wire-no-producer-ok(wire-compat alias of add_refs)
    "add_ref",
    "add_refs",
    "release",
    "release_batch",
    "release_all",
})

# ------------------------------------------------------- raylet lease plane
# Per-node local schedulers (``_private/raylet.py``, DESIGN.md §4i;
# reference analog: ``src/ray/raylet/`` NodeManager + LocalTaskManager).
# A raylet converts one GCS connection into a bidirectional lease channel
# with ``raylet_attach`` and from then on the channel carries ONLY these
# kinds — none of them ever appears on a connection that negotiated
# < PROTO_RAYLET, so old peers see byte-identical traffic (the PR-4/PR-7
# hello pattern).  All lease frames are oneways (rid None): the channel
# is a stream in both directions, never request/response — loss of the
# channel IS the failure signal (lease reclaim / node removal).
#
# Declared here, next to the frame schema, because it is a wire-level
# contract: tools/rtlint's wire pass asserts every kind has exactly one
# GCS dispatch arm (downstream set) or raylet dispatch arm (upstream
# set) plus a producer on the other side.
# One kind per line (line-anchored waivers, like REF_KINDS).

# GCS -> raylet pushes:
RAYLET_DOWN_KINDS = frozenset({
    "lease_grant",     # bulk block of task specs + their resource claims
    "lease_revoke",    # cancel: drop queued / cancel running specs
    "worker_ctl",      # forward an OOB ctl frame to a local worker
    "raylet_stop",     # clean shutdown request (head shutting down)
})
# raylet -> GCS reports:
RAYLET_UP_KINDS = frozenset({
    "raylet_attach",       # converts the conn (carried at >= PROTO_RAYLET)
    "raylet_done_batch",   # batched task completions + lease handoffs
    "raylet_ref_batch",    # netted owner-local refcount deltas (reconcile)
    "raylet_lease_return", # unstarted leases given back (idle / shutdown)
    "raylet_fwd",          # verbatim worker event (actor_ready, logs, ...)
    "raylet_worker_died",  # local worker process death (ledger cleanup)
    "raylet_task_blocked",   # leased task parked in get(): CPU released
    "raylet_task_unblocked", # ... and re-acquired
    "raylet_heartbeat",    # keepalive + local scheduler stats (the ONE
    #                        liveness path in raylet mode: no agent_attach)
    "raylet_workers",      # worker roster re-announce after a head restart
    "raylet_detach",       # clean leave: reclaim leases, remove the node
})
RAYLET_KINDS = RAYLET_DOWN_KINDS | RAYLET_UP_KINDS

# -------------------------------------------------- GCS replication plane
# Ledger replication to a warm standby head (``_private/replication.py``,
# DESIGN.md §4l; reference analog: GCS fault tolerance via Redis-backed
# table persistence).  A standby converts one GCS connection into a
# one-way replication stream with ``repl_attach`` — version-fenced at
# PROTO_REPL exactly like the raylet lease channel, so no older peer
# ever sees these kinds.  Every frame is a oneway (rid None): the
# stream's loss IS the failure signal (the standby probes the endpoint
# and promotes).  One kind per line (line-anchored waivers, like
# REF_KINDS); tools/rtlint's wire pass asserts arm + producer per kind.

# standby -> GCS:
REPL_UP_KINDS = frozenset({
    "repl_attach",     # converts the conn into the replication stream
})
# GCS -> standby pushes:
REPL_DOWN_KINDS = frozenset({
    "repl_snapshot",   # full durable-state bootstrap (+ wal position)
    "repl_wal",        # batch of ledger WAL records, seq-ordered
    "repl_heartbeat",  # liveness + current epoch/seq
    "repl_tsdb",       # head TSDB raw-ring deltas (history handoff)
})
REPL_KINDS = REPL_DOWN_KINDS | REPL_UP_KINDS

# ------------------------------------------------------------- data ops
# Request kinds the data-plane server (``data_plane.DataPlaneServer``)
# dispatches on (the ``op`` field).  Declared here, next to the control
# kind tables, so tools/rtlint's protostate pass can assert the
# ``fetch_stream`` session FSM below and the server's dispatch arms
# never drift apart.
DATA_OPS = frozenset({
    "__proto_hello__",   # data-plane version negotiation (v1+ pullers)
    "fetch_object",      # legacy size probe (seed protocol)
    "fetch_chunk",       # legacy request-per-chunk pull (seed protocol)
    "fetch_stream",      # streamed pull: ack + bulk frames (v1+)
    "delete_object",     # spool delete (invalidates the fd cache)
    "stats",             # serve counters (tests / autopilot probes)
})

# ------------------------------------------------------------ bulk frames
# Data-plane streaming (``_private/data_plane.py``): after a
# ``fetch_stream`` request/acknowledge exchange (ordinary control
# messages), the holder pushes the object's bytes as a sequence of
# length-prefixed RAW BINARY frames — no pickle, no per-chunk
# request/response round trip.  On a direct TCP connection the frames
# are written straight on the socket fd (header ``writev`` +
# ``os.sendfile`` from the spool file: the payload never enters
# userspace on the send side) and read with ``recv_into`` straight into
# the receiver's pre-sized buffer.  Through the head's message-pump
# relay (which re-frames ``recv_bytes``/``send_bytes`` messages and
# would corrupt raw fd traffic) each frame instead rides one
# ``send_bytes`` message: same zero-pickle payload, Connection framing
# as the length prefix, and a zero-length message as the abort marker.
#
# Frame header: ``[u8 kind][u32 payload length]`` big-endian.
#   BULK_DATA  payload = raw object bytes at the stream cursor
#   BULK_END   payload empty — stream complete (defensive trailer; the
#              ack already declared the exact byte count)
#   BULK_ERR   payload = utf-8 error text; the conn STAYS usable (the
#              server returns to message mode), so a pooled connection
#              survives a mid-stream miss
BULK_DATA = 0x01
BULK_END = 0x02
BULK_ERR = 0x03
_BULK_HDR = struct.Struct(">BI")
BULK_HDR_LEN = _BULK_HDR.size


def bulk_pack_header(kind: int, length: int) -> bytes:
    return _BULK_HDR.pack(kind, length)


def bulk_unpack_header(buf) -> Tuple[int, int]:
    """(kind, payload_length) from a BULK_HDR_LEN-byte header."""
    return _BULK_HDR.unpack_from(buf, 0)


# Data-plane protocol versions, negotiated per connection with the same
# ``__proto_hello__`` exchange the control plane uses (PR-2).  A legacy
# holder answers the hello with an unknown-op error and the puller
# degrades to the v0 chunk ops; a legacy puller never sends the hello
# and the server keeps speaking v0 to it.
DATA_PROTO_MIN = 0   # request-per-chunk pickled dicts (seed protocol)
DATA_PROTO_TRACE = 2  # accepts the optional TRACE_FIELD on fetch_stream
DATA_PROTO_MAX = 2   # fetch_stream + bulk frames + trace field


# ------------------------------------------------- session FSMs (§4p)
# Per-channel session state machines, declared next to the kind tables
# they constrain.  ``tools/rtlint/protostate.py`` (a) checks every
# producer and dispatch arm emits/handles only kinds these FSMs allow
# for its side, and (b) exhaustively explores each FSM across the full
# old×new version matrix (client max-version × server floor × server
# max-version) proving no reachable state deadlocks, double-replies,
# or drops a reply-expected frame.
#
# Transition tuples: ``(state, who, kind, min_version, effect, next)``
#  - who:    "c" = the dialing side, "s" = the serving side, "x" = either
#  - kind:   a wire kind, or a ``*``-prefixed pseudo-kind (a frame
#            family or event, not a literal kind string): ``*rpc`` = any
#            two-way control kind, ``*ref`` = any REF_KINDS oneway,
#            ``*reply``/``*hello_ok``/``*hello_reject`` = reply frames
#            (matched by rid, not kind), ``*bulk_*`` = raw binary bulk
#            frames, ``*eof`` = connection loss/close.
#  - min_version: the transition exists only at session version >= this
#            (the version fence: e.g. ``raylet_attach`` at PROTO_RAYLET).
#  - effect: "request" opens a reply obligation, "reply" settles one,
#            "oneway" neither, "convert" hands the conn to another
#            channel (must settle all obligations first), "teardown"
#            closes the conn (EOF settles obligations by construction —
#            the peer observes the loss).
#
# ``pre_version`` is the wire version of frames before a ``hello``
# reply pins the negotiated version; channels without a ``hello`` ride
# a control conn that already negotiated.
SESSION_FSMS = {
    # ---- control negotiation + RPC (v1..v5 matrix; ISSUE v2-v5 plus
    # the v1 floor peers still speak) ---------------------------------
    "control": {
        "versions": (PROTO_MIN, PROTO_MAX),
        "pre_version": PROTO_MIN,
        "hello": "__proto_hello__",
        "initial": "start",
        "finals": ("closed", "converted"),
        "transitions": (
            ("start", "c", "__proto_hello__", 1, "request",
             "hello_wait"),
            ("hello_wait", "s", "*hello_ok", 1, "reply", "ready"),
            ("hello_wait", "s", "*hello_reject", 1, "reply", "closed"),
            # hello-less legacy sessions stay at the floor version
            ("start", "c", "*rpc", 1, "request", "start_wait"),
            ("start_wait", "s", "*reply", 1, "reply", "start"),
            ("start", "c", "*ref", 1, "oneway", "start"),
            ("ready", "c", "*rpc", 1, "request", "ready_wait"),
            ("ready_wait", "s", "*reply", 1, "reply", "ready"),
            ("ready", "c", "*ref", 1, "oneway", "ready"),
            # channel conversions: the conn leaves the control FSM
            ("ready", "c", "attach_task_conn", 1, "convert",
             "converted"),
            ("ready", "c", "attach_worker_ctl", 1, "convert",
             "converted"),
            ("ready", "c", "agent_attach", 1, "convert", "converted"),
            ("ready", "c", "raylet_attach", PROTO_RAYLET, "convert",
             "converted"),
            ("ready", "c", "repl_attach", PROTO_REPL, "convert",
             "converted"),
            ("start", "x", "*eof", 1, "teardown", "closed"),
            ("ready", "x", "*eof", 1, "teardown", "closed"),
        ),
    },
    # ---- raylet lease channel (§4i): pure oneway streams ------------
    "raylet": {
        "versions": (PROTO_MIN, PROTO_MAX),
        "initial": "unattached",
        # "unattached" is final: at < PROTO_RAYLET the channel simply
        # never opens (the version fence, byte-identical old traffic)
        "finals": ("unattached", "closed"),
        "transitions": (
            ("unattached", "c", "raylet_attach", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_done_batch", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_ref_batch", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_lease_return", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_fwd", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_worker_died", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_task_blocked", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_task_unblocked", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_heartbeat", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "c", "raylet_workers", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "s", "lease_grant", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "s", "lease_revoke", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "s", "worker_ctl", PROTO_RAYLET,
             "oneway", "attached"),
            ("attached", "s", "raylet_stop", PROTO_RAYLET,
             "oneway", "stopping"),
            ("attached", "c", "raylet_detach", PROTO_RAYLET,
             "oneway", "closed"),
            # drain: completions/returns still flow after raylet_stop,
            # and in-flight GCS pushes may race the stop frame
            ("stopping", "c", "raylet_done_batch", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_ref_batch", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_lease_return", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_fwd", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_worker_died", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_heartbeat", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "s", "lease_grant", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "s", "lease_revoke", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "s", "worker_ctl", PROTO_RAYLET,
             "oneway", "stopping"),
            ("stopping", "c", "raylet_detach", PROTO_RAYLET,
             "oneway", "closed"),
            ("attached", "x", "*eof", PROTO_RAYLET, "teardown",
             "closed"),
            ("stopping", "x", "*eof", PROTO_RAYLET, "teardown",
             "closed"),
        ),
    },
    # ---- GCS replication stream (§4l): one-way pushes ---------------
    "repl": {
        "versions": (PROTO_MIN, PROTO_MAX),
        "initial": "unattached",
        "finals": ("unattached", "closed"),
        "transitions": (
            ("unattached", "c", "repl_attach", PROTO_REPL,
             "oneway", "syncing"),
            # a repl_wal racing the bootstrap snapshot ahead of it is
            # legal (the standby pre-buffers it; replication.py)
            ("syncing", "s", "repl_wal", PROTO_REPL,
             "oneway", "syncing"),
            ("syncing", "s", "repl_heartbeat", PROTO_REPL,
             "oneway", "syncing"),
            ("syncing", "s", "repl_tsdb", PROTO_REPL,
             "oneway", "syncing"),
            ("syncing", "s", "repl_snapshot", PROTO_REPL,
             "oneway", "streaming"),
            ("streaming", "s", "repl_wal", PROTO_REPL,
             "oneway", "streaming"),
            ("streaming", "s", "repl_heartbeat", PROTO_REPL,
             "oneway", "streaming"),
            ("streaming", "s", "repl_tsdb", PROTO_REPL,
             "oneway", "streaming"),
            ("syncing", "x", "*eof", PROTO_REPL, "teardown", "closed"),
            ("streaming", "x", "*eof", PROTO_REPL, "teardown",
             "closed"),
        ),
    },
    # ---- data-plane fetch_stream (DATA_PROTO v0..v2) ----------------
    "fetch_stream": {
        "versions": (DATA_PROTO_MIN, DATA_PROTO_MAX),
        "pre_version": DATA_PROTO_MIN,
        "hello": "__proto_hello__",
        "initial": "idle",
        "finals": ("idle", "closed"),
        "transitions": (
            ("idle", "c", "__proto_hello__", 0, "request",
             "hello_wait"),
            ("hello_wait", "s", "*hello_ok", 0, "reply", "idle"),
            # negotiation failure replies {"error"} and KEEPS the conn
            # serving seed-protocol ops (data_plane._serve)
            ("hello_wait", "s", "*hello_reject", 0, "reply", "idle"),
            ("idle", "c", "fetch_object", 0, "request", "req_wait"),
            ("idle", "c", "fetch_chunk", 0, "request", "req_wait"),
            ("idle", "c", "delete_object", 0, "request", "req_wait"),
            ("idle", "c", "stats", 0, "request", "req_wait"),
            ("req_wait", "s", "*reply", 0, "reply", "idle"),
            ("idle", "c", "fetch_stream", 1, "request", "stream_wait"),
            # {size,len} ack opens the bulk-frame phase ...
            ("stream_wait", "s", "*stream_ack", 1, "reply", "bulk"),
            # ... unless the payload rode the ack (small-range inline
            # path) or the request pre-stream missed ({"error"}: the
            # conn stays pooled, wire.py BULK_ERR contract)
            ("stream_wait", "s", "*inline_reply", 1, "reply", "idle"),
            ("stream_wait", "s", "*miss_reply", 1, "reply", "idle"),
            ("bulk", "s", "*bulk_data", 1, "oneway", "bulk"),
            ("bulk", "s", "*bulk_end", 1, "oneway", "idle"),
            ("bulk", "s", "*bulk_err", 1, "oneway", "idle"),
            ("idle", "x", "*eof", 0, "teardown", "closed"),
            ("bulk", "x", "*eof", 0, "teardown", "closed"),
        ),
    },
}

_c_codec = None
_c_codec_tried = False


def _native_codec():
    """The C rtmsg codec, or None (no toolchain / RTPU_NO_NATIVE).
    Lazy: wire.py imports during package init, ray_tpu.native cannot."""
    global _c_codec, _c_codec_tried
    if not _c_codec_tried:
        _c_codec_tried = True
        try:
            from ray_tpu.native import load_wirecodec
            _c_codec = load_wirecodec()
        except Exception:  # noqa: BLE001 - any failure → pure-Python path
            _c_codec = None
    return _c_codec


def encode_frame(obj: Any, version: int,
                 prefer_pickle: bool = False) -> bytes:
    """Encode one message at the negotiated version (0 = legacy pickle).

    ``prefer_pickle`` marks a hot-path frame (reply to a hot kind); it
    only matters when the native codec is absent — C rtmsg beats pickle,
    so with it built there is nothing to prefer.
    """
    if version == 0:
        return pickle.dumps(obj)
    if not PROTO_MIN <= version <= PROTO_MAX:
        raise ProtocolVersionError(f"cannot encode version {version}")
    if version >= 2:
        cc = _native_codec()
        if cc is not None:
            # ValueError: >200-deep nesting (C recursion guard);
            # BufferError: non-contiguous memoryview — both mean "not
            # rtmsg-able", same as TypeError: fall back to pickle
            try:
                return bytes((version, _CODEC_RTMSG)) + cc.dumps(obj)
            except (TypeError, ValueError, BufferError):
                pass  # Python-payload message → pickle codec
        elif not prefer_pickle and (not isinstance(obj, dict)
                                    or obj.get("kind") not in _HOT_KINDS):
            try:
                return bytes((version, _CODEC_RTMSG)) + rtmsg_dumps(obj)
            except TypeError:
                pass
    return bytes((version, _CODEC_PICKLE)) + pickle.dumps(obj)


def decode_frame(raw: bytes) -> Tuple[Any, int]:
    """Decode one message → (obj, observed_version)."""
    obj, ver, _codec = decode_frame_ex(raw)
    return obj, ver


def decode_frame_ex(raw: bytes) -> Tuple[Any, int, int]:
    """Decode one message → (obj, observed_version, observed_codec).

    Accepts legacy raw-pickle streams (version 0, codec reported as
    pickle) alongside framed messages, so a versioned reader can serve
    un-upgraded peers.  The codec matters to SERVERS: replies to a peer
    that spoke rtmsg must come back rtmsg (it may not be able to read
    pickle at all — the polyglot contract), while pickle-speaking peers
    keep the C-speed hot-kind reply path.
    """
    if not raw:
        raise WireError("empty frame")
    first = raw[0]
    if first == _PICKLE_OPCODE:
        return pickle.loads(raw), 0, _CODEC_PICKLE
    if first > PROTO_MAX:
        raise ProtocolVersionError(
            f"frame version {first} > supported max {PROTO_MAX}")
    if len(raw) < 2:
        raise WireError("truncated frame header")
    codec = raw[1]
    if codec == _CODEC_RTMSG:
        cc = _native_codec()
        if cc is not None:
            try:
                return cc.loads(raw[2:]), first, _CODEC_RTMSG
            except ValueError as e:
                raise WireError(str(e))
        return rtmsg_loads(raw[2:]), first, _CODEC_RTMSG
    if codec == _CODEC_PICKLE:
        return pickle.loads(raw[2:]), first, _CODEC_PICKLE
    raise WireError(f"unknown codec {codec}")


def conn_send(conn, obj: Any, version: int,
              prefer_pickle: bool = False) -> None:
    if version == 0:
        conn.send(obj)  # legacy peers do a plain pickle recv()
    else:
        conn.send_bytes(encode_frame(obj, version, prefer_pickle))


def conn_recv(conn) -> Tuple[Any, int]:
    """recv one message from a Connection → (obj, observed_version)."""
    return decode_frame(conn.recv_bytes())


def conn_recv_ex(conn) -> Tuple[Any, int, int]:
    """recv one message → (obj, observed_version, observed_codec)."""
    return decode_frame_ex(conn.recv_bytes())


def negotiate_version(client_versions, server_min: int,
                      server_max: int = PROTO_MAX) -> int:
    """Server-side half of ``__proto_hello__``: highest common version, or
    raise when the ranges are disjoint."""
    try:
        client_max = max(int(v) for v in client_versions)
    except (TypeError, ValueError):
        raise ProtocolVersionError(f"bad hello versions {client_versions!r}")
    agreed = min(server_max, client_max)
    if agreed < server_min:
        raise ProtocolVersionError(
            f"client speaks <= v{client_max}, server requires >= "
            f"v{server_min}")
    return agreed
